"""Model zoo (reference ``deeplearning4j-zoo``): standard architectures built
on the config DSL — LeNet, SimpleCNN, AlexNet, VGG16/19, ResNet50, GoogLeNet,
InceptionResNetV1, FaceNetNN4Small2, TextGenerationLSTM.

Reference ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/model/``:
``LeNet.java:35``, ``AlexNet.java``, ``VGG16.java``, ``ResNet50.java:33``
(graph built in init :82), ``GoogLeNet.java``, ``InceptionResNetV1.java``,
``FaceNetNN4Small2.java``, ``SimpleCNN.java``, ``TextGenerationLSTM.java:34``.

Architectures are the canonical published ones, NHWC, sized by
``(height, width, channels)`` so tests can instantiate miniature variants.
Pretrained-weight download (reference ``ZooModel.initPretrained`` checksum
fetch, ``ZooModel.java:40-81``) is gated on a local weights path — this
environment has no egress.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..nn.computation_graph import ComputationGraph
from ..nn.conf.computation_graph import (ElementWiseVertex, GraphBuilder,
                                         L2NormalizeVertex, MergeVertex)
from ..nn.conf.input_type import InputType
from ..nn.conf.multi_layer import NeuralNetConfiguration
from ..nn.conf.updaters import Adam, Nesterovs, UpdaterConf
from ..nn.layers.convolution import ConvolutionLayer, SubsamplingLayer
from ..nn.layers.feedforward import (ActivationLayer, DenseLayer,
                                     DropoutLayer, OutputLayer)
from ..nn.layers.normalization import (BatchNormalization,
                                       LocalResponseNormalization)
from ..nn.layers.pooling import GlobalPoolingLayer
from ..nn.layers.recurrent import LSTM, RnnOutputLayer


def _conv_block(g: GraphBuilder, name: str, inp: str, n_out: int, kernel,
                stride=(1, 1), act: Optional[str] = None,
                mode: str = "same") -> str:
    """Add a conv layer vertex; act=None inherits the builder default."""
    g.add_layer(name, ConvolutionLayer(
        n_out=n_out, kernel_size=kernel, stride=stride,
        convolution_mode=mode, activation=act), inp)
    return name


def _inception_block(g: GraphBuilder, name: str, inp: str, c1: int, c3r: int,
                     c3: int, c5r: int, c5: int, pp: int) -> str:
    """GoogLeNet-style inception module: 1x1 / 3x3 / 5x5 / pool-proj merge."""
    a = _conv_block(g, f"{name}_1x1", inp, c1, (1, 1))
    b = _conv_block(g, f"{name}_3x3r", inp, c3r, (1, 1))
    b = _conv_block(g, f"{name}_3x3", b, c3, (3, 3))
    d = _conv_block(g, f"{name}_5x5r", inp, c5r, (1, 1))
    d = _conv_block(g, f"{name}_5x5", d, c5, (5, 5))
    g.add_layer(f"{name}_pool", SubsamplingLayer(
        pooling_type="max", kernel_size=(3, 3), stride=(1, 1),
        convolution_mode="same"), inp)
    p = _conv_block(g, f"{name}_poolproj", f"{name}_pool", pp, (1, 1))
    g.add_vertex(name, MergeVertex(), a, b, d, p)
    return name


def _max_pool(g: GraphBuilder, name: str, inp: str, kernel=(3, 3),
              stride=(2, 2)) -> str:
    g.add_layer(name, SubsamplingLayer(
        pooling_type="max", kernel_size=kernel, stride=stride,
        convolution_mode="same"), inp)
    return name


@dataclass
class ZooModel:
    """Base zoo model (reference ``ZooModel.java``)."""
    model_type = "cnn"   # "cnn" | "rnn" — ModelSelector filter key
    num_classes: int = 1000
    seed: int = 123
    input_shape: Tuple[int, int, int] = (224, 224, 3)   # (h, w, c)
    updater: Optional[UpdaterConf] = None
    compute_dtype: Optional[str] = None   # 'bfloat16' = TPU fast path

    def init(self):
        raise NotImplementedError

    def pretrained(self, weights_path: Optional[str] = None):
        """Load pretrained weights (reference ``ZooModel.java:40-81``
        downloads + checksums; this environment has no egress, so the
        artifact is local).  Accepts a native checkpoint zip OR a Keras
        HDF5 file — the latter routes through the import bridge and
        transplants the weights into this zoo architecture."""
        path = weights_path or os.environ.get("DL4J_TPU_PRETRAINED_DIR")
        if not path:
            raise FileNotFoundError(
                f"no pretrained weights available for "
                f"{type(self).__name__}; pass weights_path or set "
                "DL4J_TPU_PRETRAINED_DIR")
        from ..utils import model_serializer
        if os.path.isdir(path):
            path = os.path.join(path, f"{type(self).__name__.lower()}.zip")
        with open(path, "rb") as f:
            magic = f.read(4)
        if magic == b"\x89HDF":
            return self.import_pretrained(path)
        return model_serializer.restore_model(path)

    def import_pretrained(self, keras_path: str):
        """Keras-HDF5 → zoo-architecture weight transplant (the weights-
        import bridge standing in for ``ZooModel.java``'s downloads): the
        file is imported through the Keras bridge and its parameters are
        grafted layer-for-layer onto this zoo model's own graph (so updater
        / dtype / config settings stay the zoo's)."""
        from ..modelimport.keras import import_keras_model
        imported = import_keras_model(keras_path)
        target = self.init()
        _transplant_params(imported, target,
                           what=f"{type(self).__name__} <- {keras_path}")
        return target

    def _builder(self):
        b = NeuralNetConfiguration.builder().seed(self.seed)
        if self.compute_dtype:
            b = b.compute_dtype(self.compute_dtype)
        return b


def _ordered_stateful_keys(model):
    """Keys of layers/vertices carrying params or state, in execution
    order: topological order for ComputationGraphs, layer index for
    MultiLayerNetworks."""
    has = {k for k, v in model.params.items() if v}
    has |= {k for k, v in getattr(model, "state", {}).items() if v}
    order = getattr(model.conf, "topological_order", None)
    if order:
        return [k for k in order if k in has]
    return sorted(has, key=lambda k: int(k.split("_")[-1]))


def _transplant_params(src, dst, what: str = "") -> None:
    """Copy parameters and state (e.g. BN running stats) from ``src`` onto
    ``dst`` by execution order, with shape checks — mismatches raise with
    the offending layer named rather than silently truncating.  Params and
    state ride the SAME layer pairing so a source layer missing optional
    state can never shift later layers' running stats onto the wrong
    target (state names absent on one side keep the target's values)."""
    import jax.numpy as jnp

    src_layers = _ordered_stateful_keys(src)
    dst_layers = _ordered_stateful_keys(dst)
    if len(src_layers) != len(dst_layers):
        raise ValueError(
            f"transplant {what}: source has {len(src_layers)} "
            f"param/state-bearing layers, target {len(dst_layers)} — "
            "architectures differ")
    for sk, dk in zip(src_layers, dst_layers):
        sp, dp = src.params.get(sk) or {}, dst.params.get(dk) or {}
        if set(sp) != set(dp):
            raise ValueError(f"transplant {what}: layer {dk} params "
                             f"{sorted(dp)} != source {sorted(sp)}")
        for name in sp:
            if tuple(sp[name].shape) != tuple(dp[name].shape):
                raise ValueError(
                    f"transplant {what}: {dk}.{name} shape "
                    f"{tuple(dp[name].shape)} != source "
                    f"{tuple(sp[name].shape)}")
            dp[name] = jnp.asarray(sp[name], dp[name].dtype)
        ss, ds = src.state.get(sk) or {}, dst.state.get(dk) or {}
        for name, val in ss.items():
            if name not in ds:
                continue              # optional state the target lacks
            if tuple(val.shape) != tuple(ds[name].shape):
                raise ValueError(
                    f"transplant {what}: {dk} state '{name}' shape "
                    f"{tuple(ds[name].shape)} != source {tuple(val.shape)}")
            ds[name] = jnp.asarray(val, ds[name].dtype)


@dataclass
class LeNet(ZooModel):
    """LeNet-5 (reference ``model/LeNet.java:35``)."""
    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (28, 28, 1)

    def init(self):
        h, w, c = self.input_shape
        conf = (self._builder()
                .updater(self.updater or Nesterovs(learning_rate=0.01, momentum=0.9))
                .activation("relu").weight_init("xavier")
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                        stride=(1, 1), convolution_mode="same"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                        stride=(1, 1), convolution_mode="same"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=500))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                # flat input + auto reshape, matching the reference LeNet's
                # InputType.convolutionalFlat (MnistDataSetIterator is flat)
                .set_input_type(InputType.convolutional_flat(h, w, c))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


@dataclass
class SimpleCNN(ZooModel):
    """Compact CNN (reference ``model/SimpleCNN.java``)."""
    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (48, 48, 3)

    def init(self):
        h, w, c = self.input_shape
        conf = (self._builder()
                .updater(self.updater or Adam(learning_rate=1e-3))
                .activation("relu").weight_init("relu")
                .list()
                .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalization())
                .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalization())
                .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(DropoutLayer(dropout=0.5))
                .layer(DenseLayer(n_out=256))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


@dataclass
class AlexNet(ZooModel):
    """AlexNet (reference ``model/AlexNet.java`` — one-tower variant)."""

    def init(self):
        h, w, c = self.input_shape
        conf = (self._builder()
                .updater(self.updater or Nesterovs(learning_rate=1e-2, momentum=0.9))
                .activation("relu").weight_init("relu").l2(5e-4)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                        stride=(4, 4), convolution_mode="same"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                        convolution_mode="same"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode="same"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, dropout=0.5))
                .layer(DenseLayer(n_out=4096, dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


def _vgg_blocks(cfg):
    """cfg: list of (num_convs, channels)."""
    layers = []
    for n, ch in cfg:
        for _ in range(n):
            layers.append(ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                                           convolution_mode="same"))
        layers.append(SubsamplingLayer(pooling_type="max",
                                       kernel_size=(2, 2), stride=(2, 2)))
    return layers


@dataclass
class VGG16(ZooModel):
    """VGG-16 (reference ``model/VGG16.java``)."""
    BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def init(self):
        h, w, c = self.input_shape
        b = (self._builder()
             .updater(self.updater or Nesterovs(learning_rate=1e-2, momentum=0.9))
             .activation("relu").weight_init("xavier")
             .list())
        for lyr in _vgg_blocks(self.BLOCKS):
            b.layer(lyr)
        b.layer(DenseLayer(n_out=4096, dropout=0.5))
        b.layer(DenseLayer(n_out=4096, dropout=0.5))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        conf = b.set_input_type(InputType.convolutional(h, w, c)).build()
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


@dataclass
class VGG19(VGG16):
    """VGG-19 (reference ``model/VGG19.java``)."""
    BLOCKS = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


@dataclass
class ResNet50(ZooModel):
    """ResNet-50 (reference ``model/ResNet50.java:33``, graph in init :82):
    conv/identity bottleneck blocks as a ComputationGraph with ElementWise
    residual adds."""

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        defaults = {"activation": "relu", "weight_init": "relu",
                    "updater": self.updater or
                    Nesterovs(learning_rate=1e-1, momentum=0.9)}
        if self.compute_dtype:
            defaults["compute_dtype"] = self.compute_dtype
        g = GraphBuilder(defaults, seed=self.seed)
        g.add_inputs("in").set_input_types(InputType.convolutional(h, w, c))

        def conv_bn(name, inp, n_out, kernel, stride=(1, 1), act="relu",
                    mode="same"):
            x = _conv_block(g, name, inp, n_out, kernel, stride,
                            act="identity", mode=mode)
            g.add_layer(f"{name}_bn", BatchNormalization(activation=act), x)
            return f"{name}_bn"

        def bottleneck(name, inp, filters, stride, project):
            f1, f2, f3 = filters
            x = conv_bn(f"{name}_a", inp, f1, (1, 1), stride)
            x = conv_bn(f"{name}_b", x, f2, (3, 3))
            x = conv_bn(f"{name}_c", x, f3, (1, 1), act="identity")
            if project:
                sc = conv_bn(f"{name}_sc", inp, f3, (1, 1), stride,
                             act="identity")
            else:
                sc = inp
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
            g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                        f"{name}_add")
            return f"{name}_out"

        x = conv_bn("conv1", "in", 64, (7, 7), (2, 2))
        x = _max_pool(g, "pool1", x)
        stages = [(3, (64, 64, 256), (1, 1)),
                  (4, (128, 128, 512), (2, 2)),
                  (6, (256, 256, 1024), (2, 2)),
                  (3, (512, 512, 2048), (2, 2))]
        for si, (blocks, filters, stride) in enumerate(stages):
            for bi in range(blocks):
                x = bottleneck(f"s{si}b{bi}", x, filters,
                               stride if bi == 0 else (1, 1), bi == 0)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "avgpool")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()


@dataclass
class GoogLeNet(ZooModel):
    """GoogLeNet / Inception-v1 (reference ``model/GoogLeNet.java``)."""

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        g = GraphBuilder(
            {"activation": "relu", "weight_init": "relu",
             "updater": self.updater or Adam(learning_rate=1e-3)},
            seed=self.seed)
        g.add_inputs("in").set_input_types(InputType.convolutional(h, w, c))

        x = _conv_block(g, "conv1", "in", 64, (7, 7), (2, 2))
        x = _max_pool(g, "pool1", x)
        x = _conv_block(g, "conv2r", x, 64, (1, 1))
        x = _conv_block(g, "conv2", x, 192, (3, 3))
        x = _max_pool(g, "pool2", x)
        x = _inception_block(g, "i3a", x, 64, 96, 128, 16, 32, 32)
        x = _inception_block(g, "i3b", x, 128, 128, 192, 32, 96, 64)
        x = _max_pool(g, "pool3", x)
        x = _inception_block(g, "i4a", x, 192, 96, 208, 16, 48, 64)
        x = _inception_block(g, "i4b", x, 160, 112, 224, 24, 64, 64)
        x = _inception_block(g, "i4c", x, 128, 128, 256, 24, 64, 64)
        x = _inception_block(g, "i4d", x, 112, 144, 288, 32, 64, 64)
        x = _inception_block(g, "i4e", x, 256, 160, 320, 32, 128, 128)
        x = _max_pool(g, "pool4", x)
        x = _inception_block(g, "i5a", x, 256, 160, 320, 32, 128, 128)
        x = _inception_block(g, "i5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "dropout")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()


@dataclass
class InceptionResNetV1(ZooModel):
    """Inception-ResNet v1, compact faithful rendition (reference
    ``model/InceptionResNetV1.java`` — stem + scaled residual inception
    blocks A/B/C with reduction blocks)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (160, 160, 3)
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5
    embedding_size: int = 128

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        g = GraphBuilder(
            {"activation": "relu", "weight_init": "relu",
             "updater": self.updater or Adam(learning_rate=1e-3)},
            seed=self.seed)
        g.add_inputs("in").set_input_types(InputType.convolutional(h, w, c))

        def conv(name, inp, n_out, kernel, stride=(1, 1), act="relu"):
            return _conv_block(g, name, inp, n_out, kernel, stride, act=act)

        def res_block(name, inp, branches, channels, scale=0.17):
            """Scaled residual add: out = relu(in + scale*conv(concat(branches)))."""
            outs = []
            for i, spec in enumerate(branches):
                x = inp
                for j, (n_out, kernel) in enumerate(spec):
                    x = conv(f"{name}_br{i}_{j}", x, n_out, kernel)
                outs.append(x)
            if len(outs) > 1:
                g.add_vertex(f"{name}_cat", MergeVertex(), *outs)
                cat = f"{name}_cat"
            else:
                cat = outs[0]
            up = conv(f"{name}_up", cat, channels, (1, 1), act="identity")
            from ..nn.conf.computation_graph import ScaleVertex
            g.add_vertex(f"{name}_scale", ScaleVertex(scale_factor=scale), up)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                         inp, f"{name}_scale")
            g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                        f"{name}_add")
            return name

        # stem (compact)
        x = conv("stem1", "in", 32, (3, 3), (2, 2))
        x = conv("stem2", x, 64, (3, 3))
        x = _max_pool(g, "stempool", x)
        x = conv("stem3", x, 128, (3, 3), (2, 2))
        x = conv("stem4", x, 256, (3, 3), (2, 2))
        # inception-resnet-A blocks
        for i in range(self.blocks_a):
            x = res_block(f"a{i}", x,
                          [[(32, (1, 1))],
                           [(32, (1, 1)), (32, (3, 3))],
                           [(32, (1, 1)), (32, (3, 3)), (32, (3, 3))]], 256)
        x = conv("redA", x, 384, (3, 3), (2, 2))
        for i in range(self.blocks_b):
            x = res_block(f"b{i}", x,
                          [[(128, (1, 1))],
                           [(128, (1, 1)), (128, (1, 7)), (128, (7, 1))]],
                          384, scale=0.10)
        x = conv("redB", x, 512, (3, 3), (2, 2))
        for i in range(self.blocks_c):
            x = res_block(f"c{i}", x,
                          [[(192, (1, 1))],
                           [(192, (1, 1)), (192, (1, 3)), (192, (3, 1))]],
                          512, scale=0.20)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       activation="softmax", loss="mcxent"),
                    "embeddings")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()


@dataclass
class FaceNetNN4Small2(ZooModel):
    """FaceNet NN4-small2 style embedding net (reference
    ``model/FaceNetNN4Small2.java``): inception-style trunk → L2-normalized
    embedding → center-loss softmax head."""
    num_classes: int = 100
    input_shape: Tuple[int, int, int] = (96, 96, 3)
    embedding_size: int = 128

    def init(self) -> ComputationGraph:
        from ..nn.layers.feedforward import CenterLossOutputLayer
        h, w, c = self.input_shape
        g = GraphBuilder(
            {"activation": "relu", "weight_init": "relu",
             "updater": self.updater or Adam(learning_rate=1e-3)},
            seed=self.seed)
        g.add_inputs("in").set_input_types(InputType.convolutional(h, w, c))

        x = _conv_block(g, "conv1", "in", 64, (7, 7), (2, 2))
        x = _max_pool(g, "pool1", x)
        x = _conv_block(g, "conv2", x, 192, (3, 3))
        x = _max_pool(g, "pool2", x)
        x = _inception_block(g, "i3a", x, 64, 96, 128, 16, 32, 32)
        x = _inception_block(g, "i3b", x, 64, 96, 128, 32, 64, 64)
        x = _max_pool(g, "pool3", x)
        x = _inception_block(g, "i4a", x, 256, 96, 192, 32, 64, 128)
        x = _inception_block(g, "i4e", x, 160, 112, 224, 24, 64, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"),
                    "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", CenterLossOutputLayer(
            n_out=self.num_classes, activation="softmax", loss="mcxent",
            alpha=0.9, lambda_=5e-3), "embeddings")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()


@dataclass
class TextGenerationLSTM(ZooModel):
    """Char-level text generation LSTM (reference
    ``model/TextGenerationLSTM.java:34``)."""
    model_type = "rnn"
    num_classes: int = 26          # vocab size
    timesteps: int = 40
    hidden: int = 256

    def init(self):
        conf = (self._builder()
                .updater(self.updater or Adam(learning_rate=2e-3))
                .weight_init("xavier")
                .gradient_normalization("clipelementwiseabsolutevalue", 10.0)
                .list()
                .layer(LSTM(n_out=self.hidden, activation="tanh"))
                .layer(LSTM(n_out=self.hidden, activation="tanh"))
                .layer(RnnOutputLayer(n_out=self.num_classes,
                                      activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(self.num_classes,
                                                    self.timesteps))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


ALL_MODELS = [LeNet, SimpleCNN, AlexNet, VGG16, VGG19, ResNet50, GoogLeNet,
              InceptionResNetV1, FaceNetNN4Small2, TextGenerationLSTM]


@dataclass
class TransformerLM(ZooModel):
    """Decoder-only transformer language model — the attention-era
    counterpart of TextGenerationLSTM (no reference equivalent; built from
    the TPU-native attention stack: pre-norm blocks, causal masking,
    flash/ring kernels selectable via attn_impl)."""
    model_type = "rnn"
    vocab_size: int = 256
    seq_len: int = 128
    embed: int = 256
    n_layers: int = 4
    n_heads: int = 8
    attn_impl: str = "auto"
    flash_min_seq: Optional[int] = None   # 'auto' crossover override
    moe_experts: int = 0    # >0: Switch-style sparse FFN blocks
    # integer-id targets [b, t] through the gather-based loss instead of
    # one-hot [b, t, V] — at V=8192 the one-hot path reads an extra
    # ~268 MB of HBM per step for the same value/gradients (measured in
    # BENCH_NOTES "transformer campaign"); LM training should use this
    sparse_labels: bool = False

    def init(self):
        from ..nn.layers.attention import (PositionalEncodingLayer,
                                           TransformerBlock)
        from ..nn.layers.feedforward import EmbeddingSequenceLayer
        from ..nn.layers.recurrent import RnnOutputLayer
        b = (self._builder()
             .updater(self.updater or Adam(learning_rate=3e-4))
             .weight_init("xavier")
             .list()
             .layer(EmbeddingSequenceLayer(n_out=self.embed))
             .layer(PositionalEncodingLayer()))
        for _ in range(self.n_layers):
            b = b.layer(TransformerBlock(n_heads=self.n_heads, causal=True,
                                         attn_impl=self.attn_impl,
                                         flash_min_seq=self.flash_min_seq,
                                         moe_experts=self.moe_experts))
        loss = "sparse_mcxent" if self.sparse_labels else "mcxent"
        conf = (b.layer(RnnOutputLayer(n_out=self.vocab_size,
                                       activation="softmax", loss=loss))
                .set_input_type(InputType.recurrent(self.vocab_size,
                                                    self.seq_len))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()


ALL_MODELS.append(TransformerLM)


class ModelSelector:
    """Select zoo models by name/type (reference
    ``deeplearning4j-zoo/.../ModelSelector.java``: select(ZooType) returns a
    name → instance map for benchmarking sweeps over the whole zoo)."""

    @staticmethod
    def select(*names, **init_kwargs):
        """``names``: model class names (case-insensitive), a model_type
        ("cnn"/"rnn"), or "all".  Returns {name: uninitialized instance}."""
        by_name = {cls.__name__.lower(): cls for cls in ALL_MODELS}
        out = {}
        for name in names:
            key = name.lower()
            if key == "all":
                out.update({cls.__name__: cls(**init_kwargs)
                            for cls in ALL_MODELS})
            elif key in ("cnn", "rnn"):
                out.update({cls.__name__: cls(**init_kwargs)
                            for cls in ALL_MODELS
                            if cls.model_type == key})
            elif key in by_name:
                out[by_name[key].__name__] = by_name[key](**init_kwargs)
            else:
                raise ValueError(
                    f"unknown zoo model '{name}'; available: "
                    f"{sorted(by_name)} or 'all'/'cnn'/'rnn'")
        return out
