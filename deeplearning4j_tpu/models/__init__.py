"""Model zoo (reference ``deeplearning4j-zoo``) + bench/flagship selection."""
import numpy as np


def available_bench_model():
    """Best available model+batch for bench.py — upgraded as the zoo grows."""
    from ..nn.conf.multi_layer import NeuralNetConfiguration
    from ..nn.conf.updaters import Adam
    from ..nn.conf.input_type import InputType
    from ..nn.layers.feedforward import DenseLayer, OutputLayer
    from ..nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(42).activation("relu").weight_init("xavier")
            .updater(Adam(learning_rate=1e-3))
            .list()
            .layer(DenseLayer(n_out=1024))
            .layer(DenseLayer(n_out=1024))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    model = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    batch = 512
    x = rng.standard_normal((batch, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    return model, (x, y)
