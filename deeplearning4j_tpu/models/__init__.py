"""Model zoo (reference ``deeplearning4j-zoo``) + bench/flagship selection."""
import numpy as np

from .zoo import (ALL_MODELS, AlexNet, FaceNetNN4Small2, GoogLeNet,
                  InceptionResNetV1, LeNet, ResNet50, SimpleCNN,
                  ModelSelector, TextGenerationLSTM, TransformerLM, VGG16,
                  VGG19, ZooModel)

__all__ = [
    "ALL_MODELS", "AlexNet", "FaceNetNN4Small2", "GoogLeNet",
    "InceptionResNetV1", "LeNet", "ResNet50", "SimpleCNN",
    "ModelSelector", "TextGenerationLSTM", "TransformerLM", "VGG16",
    "VGG19", "ZooModel",
    "available_bench_model", "flagship_entry_model", "generate_tokens",
]


def available_bench_model(batch: int = 32, image: int = 224,
                          compute_dtype: str = "bfloat16"):
    """Flagship bench model: ResNet50-ImageNet (the BASELINE.md north-star
    metric is ResNet50 examples/sec/chip).  bf16 compute is the TPU-native
    default (f32 master params); DL4J_TPU_BENCH_DTYPE=float32 disables.
    Returns (model, (x, y))."""
    import os
    compute_dtype = os.environ.get("DL4J_TPU_BENCH_DTYPE", compute_dtype)
    model = ResNet50(num_classes=1000,
                     compute_dtype=None if compute_dtype == "float32"
                     else compute_dtype,
                     input_shape=(image, image, 3)).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, image, image, 3), dtype=np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
    return model, (x, y)


def flagship_entry_model():
    """Small-shape flagship instance for the driver's single-chip compile
    check (same architecture, quick compile)."""
    model = ResNet50(num_classes=100, input_shape=(96, 96, 3)).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 96, 96, 3), dtype=np.float32)
    y = np.eye(100, dtype=np.float32)[rng.integers(0, 100, 8)]
    return model, (x, y)


def generate_tokens(net, prompt_ids, n_tokens: int, temperature: float = 1.0,
                    seed: int = 0):
    """Autoregressive sampling through the KV-cached ``rnn_time_step``
    stream (works for TransformerLM and recurrent LMs alike).
    prompt_ids: [batch, t0] ints.  Returns [batch, t0 + n_tokens]."""
    rng = np.random.default_rng(seed)
    prompt_ids = np.asarray(prompt_ids)
    caches = [c for c in (getattr(l, "max_cache_len", None)
                          for l in net.layers) if c]
    total = prompt_ids.shape[1] + n_tokens
    if caches and total > min(caches):
        raise ValueError(
            f"prompt + n_tokens = {total} exceeds the smallest KV cache "
            f"({min(caches)}); raise max_cache_len on the attention layers")
    net.rnn_clear_previous_state()
    probs = np.asarray(net.rnn_time_step(prompt_ids))[:, -1]   # [b, v]
    out = [prompt_ids]
    for _ in range(n_tokens):
        if temperature <= 0:
            nxt = probs.argmax(-1)
        else:
            logits = np.log(np.maximum(probs, 1e-9)) / temperature
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            nxt = np.array([rng.choice(p.shape[-1], p=row) for row in p])
        nxt = nxt.astype(prompt_ids.dtype)[:, None]
        out.append(nxt)
        probs = np.asarray(net.rnn_time_step(nxt))[:, -1]
    return np.concatenate(out, axis=1)
