"""Atomic, checksummed file commits — the one write path for durable state.

Reference posture: DL4J's ``CheckpointListener``/``ModelSerializer`` write
zips in place, so a crash mid-write leaves a truncated file that a later
``restoreMultiLayerNetwork`` explodes on.  Here every durable artifact is
committed by the POSIX temp-then-rename protocol:

  1. write the payload to a sibling temp path (same filesystem, so the
     rename below cannot degrade into a copy);
  2. flush + ``fsync`` the file descriptor (data reaches the disk, not
     just the page cache);
  3. ``os.replace`` onto the final name — atomic on POSIX: readers see
     either the old complete file or the new complete file, never a
     partial one;
  4. best-effort ``fsync`` of the parent directory so the rename itself
     survives power loss.

Checkpoint *directories* extend the same idea: stage every file in a
``.tmp-`` sibling directory, write a manifest carrying per-file SHA-256
checksums last, and commit the whole directory with one rename.  A crash
at any point leaves either the previous committed state or a ``.tmp-``
orphan that discovery ignores and ``discard_orphans`` sweeps.

This module is dependency-light on purpose (stdlib only, no package
imports): ``utils/model_serializer`` routes through it, and the
``faulttolerance.checkpoint`` store builds on it.  graftlint JX014 flags
raw ``open(.., "wb")`` / ``np.savez`` / ``zipfile.ZipFile(.., "w")``
writes to checkpoint-like paths that bypass these helpers.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import uuid
from typing import Any, Dict, Iterator, Optional

__all__ = ["atomic_file", "atomic_write_bytes", "atomic_write_json",
           "commit_dir", "staging_dir", "discard_orphans",
           "sha256_file", "TMP_PREFIX"]

TMP_PREFIX = ".tmp-"


def _fsync_path(path: str) -> None:
    """fsync a file by path; directory fsync is best-effort (some
    filesystems refuse O_RDONLY dir descriptors)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _tmp_sibling(path: str) -> str:
    """A temp name in the SAME directory as ``path`` (rename stays atomic
    only within one filesystem); unique per attempt so a crashed writer's
    leftover can't collide with a retry."""
    d, base = os.path.split(os.path.abspath(path))
    return os.path.join(d, f"{TMP_PREFIX}{base}-{os.getpid()}-"
                           f"{uuid.uuid4().hex[:8]}")


@contextlib.contextmanager
def atomic_file(path: str) -> Iterator[str]:
    """Context manager yielding a temp path; on clean exit the temp file
    is fsynced and atomically renamed onto ``path``.  On error the temp
    file is removed and nothing at ``path`` changes::

        with atomic_file(dst) as tmp:
            with zipfile.ZipFile(tmp, "w") as zf:
                ...
    """
    tmp = _tmp_sibling(path)
    try:
        yield tmp
        _fsync_path(tmp)
        os.replace(tmp, path)
        _fsync_path(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Commit ``data`` to ``path`` via temp-then-rename + fsync."""
    tmp = _tmp_sibling(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_path(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def atomic_write_json(path: str, obj: Any) -> None:
    atomic_write_bytes(path, json.dumps(obj, sort_keys=True,
                                        indent=1).encode())


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def staging_dir(final_dir: str) -> str:
    """Create and return a ``.tmp-`` sibling staging directory for
    ``final_dir`` (commit it with :func:`commit_dir`)."""
    tmp = _tmp_sibling(final_dir)
    os.makedirs(tmp)
    return tmp


def commit_dir(tmp_dir: str, final_dir: str) -> None:
    """Atomically publish a fully-staged directory: fsync every staged
    file, then rename the directory onto ``final_dir``.  An existing
    ``final_dir`` (same step re-saved) is replaced."""
    for root, _, files in os.walk(tmp_dir):
        for name in files:
            _fsync_path(os.path.join(root, name))
    _fsync_path(tmp_dir)
    try:
        os.replace(tmp_dir, final_dir)
    except OSError:
        # POSIX rename onto a non-empty directory fails: this step was
        # committed before (listener iter+epoch triggers can coincide) —
        # drop the old one and retry once
        if os.path.isdir(final_dir):
            shutil.rmtree(final_dir, ignore_errors=True)
            os.replace(tmp_dir, final_dir)
        else:
            raise
    _fsync_path(os.path.dirname(os.path.abspath(final_dir)))


def discard_orphans(directory: str,
                    log_warning=None, min_age_s: float = 0.0) -> int:
    """Remove ``.tmp-`` staging leftovers from crashed writers.  Returns
    the number removed; ``log_warning(path)`` observes each one.
    ``min_age_s`` spares staging dirs younger than that many seconds —
    a multi-writer barrier round stages under a SHARED ``.tmp-`` name,
    so a peer sweeping the store mid-round (an elastic rejoin) must not
    reclaim a round that is still being written."""
    import time
    removed = 0
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    now = time.time()
    for name in entries:
        if not name.startswith(TMP_PREFIX):
            continue
        path = os.path.join(directory, name)
        if min_age_s > 0:
            try:
                if now - os.path.getmtime(path) < min_age_s:
                    continue
            except OSError:
                continue        # vanished mid-scan: someone else's sweep
        if log_warning is not None:
            log_warning(path)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            with contextlib.suppress(OSError):
                os.remove(path)
        removed += 1
    return removed


def manifest_for(directory: str, files: Optional[Dict[str, str]] = None
                 ) -> Dict[str, Dict[str, Any]]:
    """Per-file checksum table for every regular file in ``directory``
    (or the given name->path map): ``{name: {"sha256", "bytes"}}``."""
    table: Dict[str, Dict[str, Any]] = {}
    items = (files.items() if files is not None else
             ((n, os.path.join(directory, n))
              for n in sorted(os.listdir(directory))))
    for name, path in items:
        if not os.path.isfile(path):
            continue
        table[name] = {"sha256": sha256_file(path),
                       "bytes": os.path.getsize(path)}
    return table
