"""Lease-based elastic cluster membership over the shared checkpoint store.

The reference delegates cluster membership to Spark (executors register
with the driver; a lost executor's partitions are re-executed from
lineage).  JAX has no lineage, so membership here is decoupled from the
data plane and recovery is checkpoint-mediated (TensorFlow's coordinated
checkpoint-restart posture, PAPERS.md 1605.08695): the *control plane* in
this module only decides WHO is in the cluster and WHICH round epoch a
write belongs to; restoring state after a change is the job of
``CheckpointManager`` + ``ElasticTrainer`` (a checkpoint written at world
size N seeds a rejoin at world size M — the portable-collectives
resharding argument, PAPERS.md 2112.01075).

Three pieces:

- :class:`FileLeaseStore` — leases + the membership view as atomic JSON
  files in a shared directory (the checkpoint store's filesystem: the one
  piece of infrastructure every worker already mounts).  Wall-clock
  deadlines, not intervals: leases must be comparable across processes.
- :class:`ClusterMember` — a worker's heartbeat: renews its lease on a
  background thread every ``ttl/3`` seconds; exposes the current
  membership view (generation, members) for generation-tagged writes.
- :class:`ClusterCoordinator` — evicts expired leases, admits joiners at
  ROUND boundaries only (mid-round membership never changes — the round
  in flight completes against the old view), bumps the rendezvous
  *generation* on every membership change and persists the view
  atomically.  ``accept(generation)`` is the write fence: a stale worker
  — one that missed an eviction/admission — can never push a frame into
  a newer round, because its tagged generation no longer matches.

Metrics: ``cluster_members`` / ``cluster_generation`` /
``cluster_heartbeat_age_seconds{worker}`` gauges,
``cluster_evictions_total{reason}`` / ``cluster_rejoins_total`` counters.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .atomic import atomic_write_json
from ..observability.registry import default_registry

__all__ = ["FileLeaseStore", "ClusterMember", "ClusterCoordinator",
           "ClusterView", "LeaseView", "shard_owner", "live_ranks"]

_LEASE_DIR = "membership"
_VIEW_FILE = "view.json"


def shard_owner(index: int, world_size: int) -> int:
    """Deterministic data-shard ownership: global batch ``index`` belongs
    to rank ``index % world_size``.  Depends only on (index, world_size),
    so any two workers that agree on the view agree on the split, and a
    rejoin at a different world size re-chunks without negotiation."""
    if world_size <= 0:
        raise ValueError(f"world_size must be positive, got {world_size}")
    return index % world_size


class LeaseView:
    """Read-only liveness over a :class:`FileLeaseStore`: who holds an
    unexpired lease *right now*, with payloads.  Reusable by any tier
    that needs membership without the coordinator's rank/generation
    machinery — the serving fleet's replica health rides this (a
    replica whose heartbeat stops simply falls out of :meth:`live` when
    its lease deadline passes; no eviction protocol needed)."""

    def __init__(self, store: "FileLeaseStore"):
        self.store = store

    def live(self, now: Optional[float] = None) -> Dict[int, Dict]:
        """Unexpired leases keyed by worker id (payloads included)."""
        now = time.time() if now is None else now
        return {wid: lease
                for wid, lease in self.store.all_leases().items()
                if float(lease["expires_at"]) >= now}

    def live_ids(self, now: Optional[float] = None) -> set:
        return set(self.live(now))

    def is_live(self, worker_id: int,
                now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        lease = self.store.read(int(worker_id))
        return lease is not None and float(lease["expires_at"]) >= now


def live_ranks(store: "FileLeaseStore", view: "ClusterView",
               now: Optional[float] = None) -> set:
    """Dense view-ranks of members whose lease is currently unexpired —
    the ``ShardBarrier.live_fn`` any member can evaluate: it only READS
    leases (eviction verdicts stay the coordinator's), so a barrier
    primary on a non-coordinator host can still tell "that writer's
    marker is missing because the writer is dead" from "still writing"
    and abort the round instead of waiting out the full timeout."""
    out = set()
    for wid in LeaseView(store).live_ids(now):
        rank = view.rank_of(wid)
        if rank is not None:
            out.add(rank)
    return out


@dataclass(frozen=True)
class ClusterView:
    """One rendezvous epoch: who is in, and which generation fence tags
    their writes.  ``round_index`` records the round boundary the view
    was installed at (views only ever change between rounds)."""

    generation: int
    members: Tuple[int, ...]
    round_index: int = 0

    @property
    def world_size(self) -> int:
        return len(self.members)

    def rank_of(self, worker_id: int) -> Optional[int]:
        """Dense rank by sorted worker id (the deterministic re-chunking
        key), or None for a non-member."""
        try:
            return self.members.index(worker_id)
        except ValueError:
            return None

    def to_dict(self) -> Dict:
        return {"generation": self.generation,
                "members": list(self.members),
                "round_index": self.round_index}

    @staticmethod
    def from_dict(d: Dict) -> "ClusterView":
        return ClusterView(generation=int(d["generation"]),
                           members=tuple(int(m) for m in d["members"]),
                           round_index=int(d.get("round_index", 0)))


class FileLeaseStore:
    """Leases and the membership view as atomic JSON files in a shared
    directory — the same filesystem the checkpoint store lives on, so no
    extra broker/etcd dependency.  Every write goes through
    ``faulttolerance.atomic`` (temp-then-rename): a reader never sees a
    torn lease, and a crashed writer leaves only an ignorable orphan."""

    def __init__(self, directory: str):
        self.directory = os.path.join(str(directory), _LEASE_DIR)
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------- leases
    def _lease_path(self, worker_id: int) -> str:
        return os.path.join(self.directory, f"lease-{int(worker_id):05d}.json")

    def renew(self, worker_id: int, ttl_s: float, *, incarnation: int = 0,
              payload: Optional[Dict] = None) -> Dict:
        """Write/refresh ``worker_id``'s lease: valid until wall-clock
        ``now + ttl_s`` (wall clock, not monotonic — the deadline must be
        comparable from other processes/hosts)."""
        now = time.time()
        lease = {"worker_id": int(worker_id),
                 "incarnation": int(incarnation),
                 "renewed_at": now,
                 "expires_at": now + float(ttl_s),
                 "payload": dict(payload or {})}
        atomic_write_json(self._lease_path(worker_id), lease)
        return lease

    def read(self, worker_id: int) -> Optional[Dict]:
        return self._read_file(self._lease_path(worker_id))

    @staticmethod
    def _read_file(path: str) -> Optional[Dict]:
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def all_leases(self) -> Dict[int, Dict]:
        out: Dict[int, Dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in sorted(names):
            if not (name.startswith("lease-") and name.endswith(".json")):
                continue
            lease = self._read_file(os.path.join(self.directory, name))
            if lease is not None:
                out[int(lease["worker_id"])] = lease
        return out

    def revoke(self, worker_id: int) -> bool:
        try:
            os.unlink(self._lease_path(worker_id))
            return True
        except OSError:
            return False

    # --------------------------------------------------------------- view
    def read_view(self) -> Optional[ClusterView]:
        d = self._read_file(os.path.join(self.directory, _VIEW_FILE))
        return None if d is None else ClusterView.from_dict(d)

    def write_view(self, view: ClusterView) -> None:
        atomic_write_json(os.path.join(self.directory, _VIEW_FILE),
                          view.to_dict())


class ClusterMember:
    """One worker's membership endpoint: a lease renewed on a background
    heartbeat thread, plus read access to the coordinator's view so the
    worker can tag its writes with the current generation.

    The heartbeat interval defaults to ``ttl/3``: two missed beats still
    leave slack before the lease expires, so a briefly-descheduled worker
    isn't evicted by scheduling jitter alone."""

    def __init__(self, store: FileLeaseStore, worker_id: int, *,
                 lease_ttl_s: float = 10.0,
                 heartbeat_interval_s: Optional[float] = None,
                 incarnation: int = 0,
                 payload_fn: Optional[Callable[[], Dict]] = None):
        self.store = store
        self.worker_id = int(worker_id)
        self.lease_ttl_s = float(lease_ttl_s)
        self.heartbeat_interval_s = (float(heartbeat_interval_s)
                                     if heartbeat_interval_s is not None
                                     else self.lease_ttl_s / 3.0)
        self.incarnation = int(incarnation)
        self.payload_fn = payload_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.renew_count = 0

    # ------------------------------------------------------------ control
    def renew_once(self) -> Dict:
        payload = self.payload_fn() if self.payload_fn else None
        lease = self.store.renew(self.worker_id, self.lease_ttl_s,
                                 incarnation=self.incarnation,
                                 payload=payload)
        self.renew_count += 1
        return lease

    def start(self) -> "ClusterMember":
        if self._thread is not None:
            return self
        self.renew_once()            # joiners are visible before start returns
        self._stop.clear()
        self._thread = threading.Thread(target=self._beat, daemon=True,
                                        name=f"dl4j-lease-{self.worker_id}")
        self._thread.start()
        return self

    def _beat(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            try:
                self.renew_once()
            except OSError:
                # a transient shared-FS hiccup: the next beat retries; a
                # persistent one expires the lease, which is the correct
                # outcome — the coordinator evicts an unreachable worker
                pass

    def stop(self, revoke: bool = True) -> None:
        """Stop heartbeating; ``revoke`` releases the lease immediately
        (a clean leave), otherwise it simply expires (a crash looks the
        same — that is the point of leases)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.heartbeat_interval_s + 1.0)
            self._thread = None
        if revoke:
            self.store.revoke(self.worker_id)

    def __enter__(self) -> "ClusterMember":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- view
    def view(self) -> Optional[ClusterView]:
        return self.store.read_view()

    def generation(self) -> int:
        v = self.view()
        return -1 if v is None else v.generation


class ClusterCoordinator:
    """Membership authority: sweeps expired leases, installs a new view —
    with a bumped rendezvous generation — at round boundaries only, and
    fences stale writes by generation.

    Round-boundary admission keeps the data plane simple: the round in
    flight always completes against the view it started with; a joiner
    (or an eviction) takes effect at the NEXT ``begin_round``.  A worker
    that missed the change keeps tagging frames with the old generation,
    and ``accept`` rejects them — it can never write into a newer round.
    """

    def __init__(self, store: FileLeaseStore, *, lease_ttl_s: float = 10.0,
                 registry=None):
        self.store = store
        self.lease_ttl_s = float(lease_ttl_s)
        self._registry = registry
        existing = store.read_view()
        self.view = existing if existing is not None else ClusterView(
            generation=0, members=())
        self.evicted_total = 0
        self.rejoined_total = 0
        reg = self._reg()
        if reg.enabled:
            # pre-register at zero: a scrape sees the full metric set the
            # moment a coordinator exists, not after the first incident
            reg.counter("cluster_evictions_total",
                        "Workers evicted from the membership view",
                        ("reason",)).labels("lease_expired").inc(0)
            reg.counter("cluster_rejoins_total",
                        "Workers (re)admitted into an existing cluster "
                        "at a round boundary").inc(0)

    def _reg(self):
        return self._registry if self._registry is not None \
            else default_registry()

    # ------------------------------------------------------------ sweeps
    def sweep(self, now: Optional[float] = None
              ) -> Tuple[Dict[int, Dict], List[int]]:
        """Partition leases into (live, evicted); expired leases are
        revoked on the spot so a later joiner with the same id starts
        from a clean slate."""
        now = time.time() if now is None else now
        leases = self.store.all_leases()
        live: Dict[int, Dict] = {}
        evicted: List[int] = []
        for wid, lease in leases.items():
            if float(lease["expires_at"]) < now:
                # re-read before the verdict: the worker may have renewed
                # between the directory scan and now (read-then-revoke
                # TOCTOU) — deleting a fresh lease would evict a live
                # heartbeating worker.  The residual window (re-read to
                # unlink) is microseconds against a ttl/3 beat period.
                cur = self.store.read(wid)
                lease = cur if cur is not None else lease
            if float(lease["expires_at"]) >= now:
                live[wid] = lease
            else:
                evicted.append(wid)
                self.store.revoke(wid)
        if evicted:
            self.evicted_total += len(evicted)
            reg = self._reg()
            if reg.enabled:
                reg.counter("cluster_evictions_total",
                            "Workers evicted from the membership view",
                            ("reason",)).labels("lease_expired").inc(
                                len(evicted))
        self._observe(live, now)
        return live, evicted

    def _observe(self, live: Dict[int, Dict], now: float) -> None:
        reg = self._reg()
        if not reg.enabled:
            return
        reg.gauge("cluster_members",
                  "Live workers holding an unexpired lease"
                  ).set(len(live))
        reg.gauge("cluster_generation",
                  "Current rendezvous generation of the membership view"
                  ).set(self.view.generation)
        age = reg.gauge("cluster_heartbeat_age_seconds",
                        "Seconds since a worker last renewed its lease",
                        ("worker",))
        for wid, lease in live.items():
            age.labels(str(wid)).set(
                max(0.0, now - float(lease["renewed_at"])))

    # ---------------------------------------------------------- rendezvous
    def begin_round(self, round_index: int) -> ClusterView:
        """Round-boundary rendezvous: sweep leases, and if the live set
        differs from the current view install a new view with a bumped
        generation.  Returns the view the round must run under."""
        live, _ = self.sweep()
        members = tuple(sorted(live))
        if members != self.view.members:
            joiners = [m for m in members if m not in self.view.members]
            rejoins = sum(1 for m in joiners
                          if int(live[m].get("incarnation", 0)) > 0
                          or self.view.generation > 0)
            if rejoins:
                self.rejoined_total += rejoins
                reg = self._reg()
                if reg.enabled:
                    reg.counter("cluster_rejoins_total",
                                "Workers (re)admitted into an existing "
                                "cluster at a round boundary").inc(rejoins)
            self.view = ClusterView(generation=self.view.generation + 1,
                                    members=members,
                                    round_index=int(round_index))
            self.store.write_view(self.view)
        elif self.view.round_index != int(round_index):
            # same membership: only advance the recorded round (no
            # generation bump — nothing a stale worker could exploit)
            self.view = ClusterView(generation=self.view.generation,
                                    members=members,
                                    round_index=int(round_index))
            self.store.write_view(self.view)
        self._observe(live, time.time())
        return self.view

    def accept(self, generation: int) -> bool:
        """The write fence: a frame tagged with ``generation`` is valid
        only if it matches the installed view — a worker evicted (or
        superseded by its own replacement) keeps the old generation and
        its late writes are dropped, never merged into a newer round."""
        return int(generation) == self.view.generation

    def expect_members(self, want: Sequence[int], *, timeout_s: float,
                       poll_s: float = 0.05) -> Dict[int, Dict]:
        """Block until every worker in ``want`` holds a live lease (initial
        rendezvous), or raise ``TimeoutError`` listing the absentees."""
        deadline = time.time() + float(timeout_s)
        while True:
            live, _ = self.sweep()
            missing = [w for w in want if w not in live]
            if not missing:
                return live
            if time.time() > deadline:
                raise TimeoutError(
                    f"cluster rendezvous incomplete: workers {missing} "
                    f"never acquired a lease within {timeout_s:.1f}s")
            time.sleep(poll_s)
