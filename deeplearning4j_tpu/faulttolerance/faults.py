"""Deterministic fault injection + retry policy for the training masters.

The reference recovers lost Spark partitions by lineage re-execution;
our thread-based masters (``parallel/master.py``) need the same property
— and a way to PROVE it.  :class:`FaultInjector` is a seeded, fully
deterministic test harness the masters consult at batch boundaries:

- ``fail(worker, rnd, times)``   raise before the round's first batch on
  the next ``times`` attempts (``times=-1``: permanently);
- ``delay(worker, rnd, seconds)`` sleep before the round's first batch
  (straggler simulation, drives the master's straggler timeout);
- ``drop(worker, rnd, times)``   complete the round's work but discard
  the result (the master treats a dropped result as a failed attempt and
  retries from the round-start snapshot).

Optionally ``fail_rate`` injects seeded random failures for soak-style
tests; everything is reproducible from the seed.

:class:`RetryPolicy` owns the per-worker retry budget and seeded
exponential backoff with jitter (decorrelated sleeps so N workers
retrying the same dead dependency don't stampede in lockstep).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["FaultInjector", "InjectedWorkerFault", "RetryPolicy"]


class InjectedWorkerFault(RuntimeError):
    """Raised by FaultInjector in a worker's execution path."""

    def __init__(self, worker: int, rnd: int, kind: str):
        self.worker, self.rnd, self.kind = worker, rnd, kind
        super().__init__(
            f"injected {kind}: worker {worker}, round {rnd}")


class FaultInjector:
    """Deterministic fault plans keyed by (worker, round); thread-safe by
    construction (each plan entry is consumed by exactly one worker)."""

    def __init__(self, seed: int = 0, fail_rate: float = 0.0):
        self.seed = seed
        self.fail_rate = float(fail_rate)
        self._rng = np.random.default_rng(seed)
        self._fail: Dict[Tuple[int, int], int] = {}
        self._delay: Dict[Tuple[int, int], float] = {}
        self._drop: Dict[Tuple[int, int], int] = {}
        self.events: List[Tuple[str, int, int]] = []   # (kind, worker, rnd)

    # ------------------------------------------------------------- plans
    def fail(self, worker: int, rnd: int, times: int = 1) -> "FaultInjector":
        """Worker ``worker`` raises at the start of round ``rnd`` for the
        next ``times`` attempts (-1 = every attempt: a permanent loss)."""
        self._fail[(worker, rnd)] = times
        return self

    def delay(self, worker: int, rnd: int, seconds: float) -> "FaultInjector":
        """Worker ``worker`` sleeps ``seconds`` before round ``rnd``'s
        first batch (every attempt) — straggler simulation."""
        self._delay[(worker, rnd)] = float(seconds)
        return self

    def drop(self, worker: int, rnd: int, times: int = 1) -> "FaultInjector":
        """Worker ``worker`` completes round ``rnd`` but its result is
        discarded for the next ``times`` attempts."""
        self._drop[(worker, rnd)] = times
        return self

    # ------------------------------------------------------------- hooks
    def on_batch(self, worker: int, rnd: int, batch_index: int) -> None:
        """Master-side hook before each batch of a worker's round chunk.
        First-batch position carries the planned fault/delay."""
        if batch_index != 0:
            return
        key = (worker, rnd)
        delay = self._delay.get(key)
        if delay:
            self.events.append(("delay", worker, rnd))
            time.sleep(delay)
        n = self._fail.get(key, 0)
        if n != 0:
            if n > 0:
                self._fail[key] = n - 1
            self.events.append(("fail", worker, rnd))
            raise InjectedWorkerFault(worker, rnd, "failure")
        if self.fail_rate and self._rng.random() < self.fail_rate:
            self.events.append(("fail", worker, rnd))
            raise InjectedWorkerFault(worker, rnd, "random failure")

    def should_drop(self, worker: int, rnd: int) -> bool:
        """Master-side hook after a worker finishes its round chunk."""
        key = (worker, rnd)
        n = self._drop.get(key, 0)
        if n == 0:
            return False
        if n > 0:
            self._drop[key] = n - 1
        self.events.append(("drop", worker, rnd))
        return True


class RetryPolicy:
    """Per-worker retry budget + seeded exponential backoff with jitter.

    Delay for attempt ``k`` (1-based) is ``base * 2**(k-1) * u`` with
    ``u ~ Uniform(0.5, 1.5)`` drawn from a seeded stream — bounded, and
    decorrelated across workers/attempts.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.05,
                 max_backoff_s: float = 5.0, seed: int = 0):
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._rng = np.random.default_rng(seed)

    def backoff(self, attempt: int) -> float:
        """Jittered delay (seconds) before retry ``attempt`` (1-based)."""
        base = self.backoff_s * (2.0 ** max(attempt - 1, 0))
        return float(min(base * self._rng.uniform(0.5, 1.5),
                         self.max_backoff_s))

    def sleep(self, attempt: int, sleep=time.sleep) -> float:
        d = self.backoff(attempt)
        if d > 0:
            sleep(d)
        return d
