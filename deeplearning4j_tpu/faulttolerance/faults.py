"""Deterministic fault injection + retry policy for the training masters.

The reference recovers lost Spark partitions by lineage re-execution;
our thread-based masters (``parallel/master.py``) need the same property
— and a way to PROVE it.  :class:`FaultInjector` is a seeded, fully
deterministic test harness the masters consult at batch boundaries:

- ``fail(worker, rnd, times)``   raise before the round's first batch on
  the next ``times`` attempts (``times=-1``: permanently);
- ``delay(worker, rnd, seconds)`` sleep before the round's first batch
  (straggler simulation, drives the master's straggler timeout);
- ``drop(worker, rnd, times)``   complete the round's work but discard
  the result (the master treats a dropped result as a failed attempt and
  retries from the round-start snapshot).

Optionally ``fail_rate`` injects seeded random failures for soak-style
tests; everything is reproducible from the seed.

:class:`RetryPolicy` owns the per-worker retry budget and seeded
exponential backoff with jitter (decorrelated sleeps so N workers
retrying the same dead dependency don't stampede in lockstep).  Each
worker draws from its OWN ``default_rng((seed, worker))`` stream: numpy
Generators are not thread-safe, so N workers sharing one generator under
concurrency would race its state — and the race would also make the
"deterministic from the seed" property a lie (draw order would depend on
thread scheduling).  Per-worker streams are both safe and
schedule-independent.

:class:`ChaosSchedule` promotes the injector to PROCESS level: a seeded
plan that can SIGKILL a worker process mid-round, partition/delay a
broker link for a window (via :class:`ChaosBroker`), and hard-crash a
process mid-checkpoint-commit (via the ``CheckpointManager.chaos``
hook) — all deterministic from the seed, driving the soak tests that
prove training completes with the correct final params after every
injected fault.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.clock import monotonic_s

__all__ = ["FaultInjector", "InjectedWorkerFault", "RetryPolicy",
           "ChaosSchedule", "ChaosBroker"]


def _record_fault(type: str, **fields) -> None:
    """Mirror an injected fault into the flight recorder's ``cluster``
    channel — a chaos soak's dump shows the faults interleaved with the
    heartbeats and evictions they caused."""
    from ..observability.recorder import get_flight_recorder
    rec = get_flight_recorder()
    if rec is not None:
        rec.record("cluster", type, **fields)


class InjectedWorkerFault(RuntimeError):
    """Raised by FaultInjector in a worker's execution path."""

    def __init__(self, worker: int, rnd: int, kind: str):
        self.worker, self.rnd, self.kind = worker, rnd, kind
        super().__init__(
            f"injected {kind}: worker {worker}, round {rnd}")


class FaultInjector:
    """Deterministic fault plans keyed by (worker, round); thread-safe by
    construction (each plan entry is consumed by exactly one worker)."""

    def __init__(self, seed: int = 0, fail_rate: float = 0.0):
        self.seed = seed
        self.fail_rate = float(fail_rate)
        self._rng = np.random.default_rng(seed)
        self._fail: Dict[Tuple[int, int], int] = {}
        self._delay: Dict[Tuple[int, int], float] = {}
        self._drop: Dict[Tuple[int, int], int] = {}
        self.events: List[Tuple[str, int, int]] = []   # (kind, worker, rnd)
        # recovery-time observability (bench.py recovery_time_ms): per
        # faulted worker, the first fault-free on_batch afterwards marks
        # the first post-recovery step — either the worker's own retry
        # attempt, or (elastic degradation, rnd == -1) a survivor
        # replaying the lost worker's chunk
        self.last_fault_s: Dict[int, float] = {}
        self.recoveries_s: List[float] = []

    # ------------------------------------------------------------- plans
    def fail(self, worker: int, rnd: int, times: int = 1) -> "FaultInjector":
        """Worker ``worker`` raises at the start of round ``rnd`` for the
        next ``times`` attempts (-1 = every attempt: a permanent loss)."""
        self._fail[(worker, rnd)] = times
        return self

    def delay(self, worker: int, rnd: int, seconds: float) -> "FaultInjector":
        """Worker ``worker`` sleeps ``seconds`` before round ``rnd``'s
        first batch (every attempt) — straggler simulation."""
        self._delay[(worker, rnd)] = float(seconds)
        return self

    def drop(self, worker: int, rnd: int, times: int = 1) -> "FaultInjector":
        """Worker ``worker`` completes round ``rnd`` but its result is
        discarded for the next ``times`` attempts."""
        self._drop[(worker, rnd)] = times
        return self

    # ------------------------------------------------------------- hooks
    def on_batch(self, worker: int, rnd: int, batch_index: int) -> None:
        """Master-side hook before each batch of a worker's round chunk.
        First-batch position carries the planned fault/delay."""
        if batch_index != 0:
            self._mark_recovered(worker, rnd)
            return
        key = (worker, rnd)
        delay = self._delay.get(key)
        if delay:
            self.events.append(("delay", worker, rnd))
            _record_fault("injected_delay", worker=worker, round=rnd,
                          seconds=delay)
            time.sleep(delay)
        n = self._fail.get(key, 0)
        if n != 0:
            if n > 0:
                self._fail[key] = n - 1
            self.events.append(("fail", worker, rnd))
            _record_fault("injected_fail", worker=worker, round=rnd)
            self.last_fault_s[worker] = monotonic_s()
            raise InjectedWorkerFault(worker, rnd, "failure")
        if self.fail_rate and self._rng.random() < self.fail_rate:
            self.events.append(("fail", worker, rnd))
            _record_fault("injected_fail", worker=worker, round=rnd)
            self.last_fault_s[worker] = monotonic_s()
            raise InjectedWorkerFault(worker, rnd, "random failure")
        self._mark_recovered(worker, rnd)

    def _mark_recovered(self, worker: int, rnd: int) -> None:
        """A fault-free batch hook after an injected failure = the first
        post-recovery step; the gap is what bench.py's recovery_time_ms
        reports.  The faulted worker's own clean attempt resolves its
        fault (sync retry path); a replay batch (``rnd == -1``) run by a
        survivor resolves the oldest pending fault (elastic path — the
        lost worker never runs again)."""
        t = self.last_fault_s.pop(worker, None)
        if t is None and rnd == -1 and self.last_fault_s:
            oldest = min(self.last_fault_s, key=self.last_fault_s.get)
            t = self.last_fault_s.pop(oldest)
        if t is not None:
            self.recoveries_s.append(monotonic_s() - t)

    def should_drop(self, worker: int, rnd: int) -> bool:
        """Master-side hook after a worker finishes its round chunk."""
        key = (worker, rnd)
        n = self._drop.get(key, 0)
        if n == 0:
            return False
        if n > 0:
            self._drop[key] = n - 1
        self.events.append(("drop", worker, rnd))
        return True


class RetryPolicy:
    """Per-worker retry budget + seeded exponential backoff with jitter.

    Delay for attempt ``k`` (1-based) is ``base * 2**(k-1) * u`` with
    ``u ~ Uniform(0.5, 1.5)`` drawn from the calling worker's OWN seeded
    stream (``default_rng((seed, worker))``) — bounded, decorrelated
    across workers/attempts, and safe under concurrency: numpy Generators
    are not thread-safe, so a single shared stream raced by N worker
    threads would corrupt generator state AND make the draw order (hence
    the delays) depend on thread scheduling.  Per-worker streams keep
    every worker's backoff sequence deterministic regardless of how the
    threads interleave.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.05,
                 max_backoff_s: float = 5.0, seed: int = 0):
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.seed = int(seed)
        self._rngs: Dict[int, np.random.Generator] = {}
        self._rng_lock = threading.Lock()

    def _stream(self, worker: int) -> np.random.Generator:
        # the dict mutation is the only shared-state write; the generator
        # itself is only ever advanced by its own worker afterwards
        with self._rng_lock:
            rng = self._rngs.get(worker)
            if rng is None:
                rng = self._rngs[worker] = np.random.default_rng(
                    (self.seed, int(worker)))
            return rng

    def backoff(self, attempt: int, worker: int = 0) -> float:
        """Jittered delay (seconds) before retry ``attempt`` (1-based) of
        ``worker``'s task."""
        base = self.backoff_s * (2.0 ** max(attempt - 1, 0))
        return float(min(base * self._stream(worker).uniform(0.5, 1.5),
                         self.max_backoff_s))

    def sleep(self, attempt: int, worker: int = 0, sleep=time.sleep) -> float:
        d = self.backoff(attempt, worker)
        if d > 0:
            sleep(d)
        return d


# ------------------------------------------------------------------- chaos
class ChaosSchedule:
    """Seeded, process-level chaos plan — the cluster runtime's proof rig.

    Where :class:`FaultInjector` raises exceptions inside a cooperative
    worker, ``ChaosSchedule`` attacks the PROCESS boundary, which is what
    a real cluster loses:

    - ``kill_process(worker, after_s)`` — SIGKILL the worker's OS process
      ``after_s`` seconds into the run (no cleanup, no goodbye: the lease
      simply stops renewing);
    - ``partition(start_s, duration_s, topic=, mode=, delay_s=)`` — a
      broker-link fault window applied by :class:`ChaosBroker`:
      ``mode="delay"`` holds each publish for ``delay_s``, ``mode="drop"``
      discards it (at-most-once transports must tolerate this);
    - ``crash_in_commit(step, stage)`` — hard ``os._exit`` between a
      checkpoint's staged file writes (attach the schedule to
      ``CheckpointManager.chaos``): the commit rename never runs, so
      recovery must skip the ``.tmp-`` orphan and restore the previous
      complete checkpoint.

    Explicit plans are trivially deterministic; ``randomized`` draws
    kill targets/times from ``default_rng(seed)`` so soak tests replay
    bit-identically from the seed.  Executed events land in ``events``
    for assertions.
    """

    CRASH_EXIT_CODE = 23    # distinguishable from SIGKILL and from rc 0

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._kills: List[Tuple[int, float]] = []       # (worker, after_s)
        self._partitions: List[Dict] = []
        self._commit_crashes: Dict[int, int] = {}       # step -> stage
        self.events: List[Tuple] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monkey: Optional[threading.Thread] = None
        self._t0: Optional[float] = None

    # ------------------------------------------------------------- plans
    def kill_process(self, worker: int, after_s: float) -> "ChaosSchedule":
        """SIGKILL ``worker``'s process ``after_s`` seconds after
        :meth:`start` (the mid-round host loss)."""
        self._kills.append((int(worker), float(after_s)))
        return self

    def partition(self, start_s: float, duration_s: float, *,
                  topic: Optional[str] = None, mode: str = "delay",
                  delay_s: float = 0.2) -> "ChaosSchedule":
        """Degrade a broker link for ``[start_s, start_s + duration_s)``:
        ``topic=None`` hits every topic; ``mode`` is ``delay`` or
        ``drop``."""
        if mode not in ("delay", "drop"):
            raise ValueError(f"partition mode must be delay|drop, got "
                             f"{mode!r}")
        self._partitions.append({"start": float(start_s),
                                 "end": float(start_s) + float(duration_s),
                                 "topic": topic, "mode": mode,
                                 "delay_s": float(delay_s)})
        return self

    def crash_in_commit(self, step: int, stage: int = 1) -> "ChaosSchedule":
        """Hard-exit the process between checkpoint staging writes of the
        checkpoint at ``step``.  Dense/single-writer sharded saves fire
        stage 1 (after model.zip / container) and 2 (after rng.npy /
        shard blocks).  A multi-writer BARRIER save fires 1 (primary:
        container+topology staged), 2 (any writer: shard bytes staged,
        completion marker NOT yet posted — "killed mid-block"), 3
        (primary: every marker landed, nothing committed — "killed
        between barrier and commit") and 4 (primary: manifest written,
        rename not yet run)."""
        self._commit_crashes[int(step)] = int(stage)
        return self

    @classmethod
    def randomized(cls, seed: int, workers: Sequence[int],
                   horizon_s: float, kills: int = 1) -> "ChaosSchedule":
        """A seeded random plan: ``kills`` SIGKILLs spread uniformly over
        ``horizon_s`` across ``workers`` — same seed, same plan."""
        sched = cls(seed)
        workers = list(workers)
        for _ in range(int(kills)):
            wid = int(workers[int(sched._rng.integers(len(workers)))])
            sched.kill_process(wid, float(sched._rng.uniform(0, horizon_s)))
        return sched

    # --------------------------------------------------------- execution
    def arm(self) -> "ChaosSchedule":
        """Zero the schedule clock (partition windows are relative to
        this).  ``start`` arms implicitly."""
        if self._t0 is None:
            self._t0 = monotonic_s()
        return self

    def elapsed(self) -> float:
        self.arm()
        return monotonic_s() - self._t0

    def start(self, pids: Callable[[], Dict[int, int]]) -> "ChaosSchedule":
        """Launch the chaos monkey thread.  ``pids()`` maps worker id ->
        live OS pid (called at fire time, so respawned incarnations are
        targeted correctly)."""
        self.arm()
        if self._monkey is not None or not self._kills:
            return self
        self._stop.clear()
        self._monkey = threading.Thread(
            target=self._run_kills, args=(pids,), daemon=True,
            name="dl4j-chaos-monkey")
        self._monkey.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monkey is not None:
            self._monkey.join(timeout=5.0)
            self._monkey = None

    def _run_kills(self, pids: Callable[[], Dict[int, int]]) -> None:
        for worker, after_s in sorted(self._kills, key=lambda k: k[1]):
            wait = after_s - self.elapsed()
            if wait > 0 and self._stop.wait(wait):
                return
            pid = pids().get(worker)
            if pid is None:
                with self._lock:
                    self.events.append(("kill_miss", worker, after_s))
                continue
            try:
                os.kill(pid, signal.SIGKILL)
                with self._lock:
                    self.events.append(("kill", worker, pid, after_s))
                # the killing side is the one that survives to dump: the
                # chaos fault lands on the cluster channel alongside the
                # victim's final heartbeats
                _record_fault("chaos_kill", worker=worker, pid=pid,
                              after_s=after_s)
                from ..observability.recorder import get_flight_recorder
                rec = get_flight_recorder()
                if rec is not None:
                    rec.maybe_dump("chaos_fault")
            except (OSError, ProcessLookupError):
                with self._lock:
                    self.events.append(("kill_miss", worker, after_s))

    # ------------------------------------------------------------- hooks
    def on_commit_stage(self, step: int, stage: int) -> None:
        """CheckpointManager hook: called between staged file writes; a
        matching plan entry hard-exits the process mid-commit."""
        if self._commit_crashes.get(int(step)) == int(stage):
            # the event can't be observed from this process again — leave
            # a breadcrumb on disk semantics instead: the .tmp- orphan IS
            # the evidence the recovery path must cope with
            os._exit(self.CRASH_EXIT_CODE)

    def link_state(self, topic: str) -> Tuple[str, float]:
        """Current fault on ``topic``'s link: ``("ok"|"delay"|"drop",
        delay_seconds)``."""
        now = self.elapsed()
        for p in self._partitions:
            if p["start"] <= now < p["end"] and \
                    (p["topic"] is None or p["topic"] == topic):
                return p["mode"], p["delay_s"]
        return "ok", 0.0


class ChaosBroker:
    """Broker proxy that applies a :class:`ChaosSchedule`'s partition
    windows to the publish path (subscriptions pass through: a partition
    models the LINK, and the transports here deliver at publish time).
    Drop-in for any publish/subscribe broker."""

    def __init__(self, inner, schedule: ChaosSchedule):
        self.inner = inner
        self.schedule = schedule

    def publish(self, topic: str, payload: bytes) -> None:
        mode, delay_s = self.schedule.link_state(topic)
        if mode == "drop":
            with self.schedule._lock:
                self.schedule.events.append(("drop_publish", topic))
            return
        if mode == "delay":
            with self.schedule._lock:
                self.schedule.events.append(("delay_publish", topic))
            time.sleep(delay_s)
        self.inner.publish(topic, payload)

    def subscribe(self, topic: str, ack: bool = False):
        return self.inner.subscribe(topic, ack=ack)

    def unsubscribe(self, topic: str, sub) -> None:
        if hasattr(self.inner, "unsubscribe"):
            self.inner.unsubscribe(topic, sub)

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()
