"""Crash-consistent checkpoint store + exact training resume.

The reference stack treats checkpointing as a first-class production
concern (``ModelSerializer`` + ``CheckpointListener`` + the earlystopping
savers, SURVEY §5); TensorFlow (PAPERS.md, 1605.08695) argues that at
production scale fault tolerance is cheap periodic checkpointing plus
automatic recovery, not per-op reliability.  This module is that layer.

**Store layout** — one directory per step, committed atomically::

    <dir>/
      ckpt-00000042/
        manifest.json        step/epoch/iteration/metric + per-file sha256
        model.zip            utils/model_serializer container (params, state,
                             updater, conf) — restorable on its own
        rng.npy              the network's PRNG key at snapshot time
        training_state.json  data-pipeline cursor (fit epoch + batch seq),
                             ShapePolicy bucket history, metric

Writes stage into a ``.tmp-`` sibling, write the manifest (checksums)
last, then commit with ONE ``os.replace`` — discovery (``latest()``)
never sees a partial directory, and a checksum-corrupt committed one is
skipped with a warning instead of crashing the restore path.

**Snapshot semantics**: ``save()`` snapshots device state to host copies
*without* ``clone()`` — clone splits the parent RNG stream, so a
clone-based snapshot would make a checkpointed run diverge from an
uncheckpointed one.  Checkpointing is an observer: byte-identical
training with or without it.  Background saves run on one worker thread
(double-buffered: the snapshot is taken synchronously — cheap host
copies — and at most one write is in flight; a second save joins the
first).

**Resume**: ``CheckpointConfig``/``resume_from=`` on the networks' ``fit``
restore params + updater + RNG + cursors so an interrupted-then-resumed
run reproduces the uninterrupted run's params exactly (tier-1 parity
test), and the restored ShapePolicy bucket history keeps padding
decisions — and therefore compiled shapes — identical on resume.

Metrics (observability registry): ``checkpoint_write_seconds{mode}``,
``checkpoint_bytes``, ``checkpoint_restore_total{result}``.
"""
from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .atomic import (TMP_PREFIX, atomic_write_json, commit_dir, manifest_for,
                     sha256_file, staging_dir)
from ..observability.clock import monotonic_s
from ..observability.registry import default_registry
from ..observability.tracer import get_tracer

__all__ = ["CheckpointManager", "CheckpointConfig", "CorruptCheckpointError",
           "FitCheckpointer", "ShardBarrier", "ShardBarrierError",
           "resume_network"]

_SHARD_FILE_RE = re.compile(r"^shards-p(\d{2,})\.npz$")
_BLOCK_MARKER_RE = re.compile(r"^block-p(\d{2,})\.json$")

log = logging.getLogger("deeplearning4j_tpu.faulttolerance")

_CKPT_RE = re.compile(r"^ckpt-(\d{8,})$")
_MANIFEST_VERSION = 1
# checkpoint write wall times: ms-scale toy nets to minutes-long pods
_WRITE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                  30.0, 60.0, 300.0)
# checkpoint sizes: KB-scale tests to multi-GB production models
_BYTES_BUCKETS = (1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11)


class CorruptCheckpointError(RuntimeError):
    """A checkpoint directory is partial or fails checksum verification."""

    def __init__(self, path, detail: str):
        self.path = str(path)
        super().__init__(f"corrupt checkpoint {self.path}: {detail}")


def _rng_to_np(key) -> Tuple[np.ndarray, bool]:
    """PRNG key -> (raw uint32 data, was_typed).  Handles both legacy
    uint32 keys and new-style typed keys."""
    import jax
    try:
        return np.array(key), False
    except TypeError:
        return np.array(jax.random.key_data(key)), True


def _np_to_rng(data: np.ndarray, typed: bool):
    import jax
    import jax.numpy as jnp
    arr = jnp.asarray(data)
    return jax.random.wrap_key_data(arr) if typed else arr


def _host_copy(tree):
    """Device pytree -> owned host-numpy pytree (donation-safe: the next
    train step may donate the originals' buffers)."""
    import jax
    return jax.tree_util.tree_map(lambda a: np.array(a), tree)


class _Snapshot:
    """The minimal surface ``model_serializer.write_model`` needs, holding
    OWNED host copies — taken synchronously so the background writer never
    races live training, and without ``clone()`` so the network's RNG
    stream is untouched (see module doc)."""

    def __init__(self, net):
        self.net_class = type(net).__name__
        self.conf = net.conf           # read-only after resolve()
        self.params = _host_copy(net.params)
        self.state = _host_copy(net.state)
        self.opt_state = None if net.opt_state is None \
            else _host_copy(net.opt_state)
        self.iteration = int(net.iteration)
        self.step = self.iteration      # dir-naming step; save() may override
        self.epoch = int(net.epoch)
        self.rng, self.rng_typed = _rng_to_np(net._rng)
        pol = getattr(net, "shape_policy", None)
        self.shape_policy = pol.snapshot() if pol is not None else None


def _tree_items(tree, prefix: str = ""):
    """Flatten a nested-dict pytree to sorted ``('layer_0/W', leaf)``
    pairs — the stable key space the sharded checkpoint format indexes
    params by."""
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            sub = f"{prefix}/{k}" if prefix else str(k)
            out.extend(_tree_items(tree[k], sub))
    else:
        out.append((prefix, tree))
    return out


def _copy_dict_tree(tree):
    """Structural copy of a nested-dict pytree (dicts copied, leaves
    shared) — the staging target for restore_sharded's swap-on-success."""
    if isinstance(tree, dict):
        return {k: _copy_dict_tree(v) for k, v in tree.items()}
    return tree


def _parent_of(tree, key: str) -> Tuple[dict, str]:
    parts = key.split("/")
    node = tree
    for p in parts[:-1]:
        nxt = node.get(p) if isinstance(node, dict) else None
        if not isinstance(nxt, dict):
            raise ValueError(f"checkpoint param key {key!r} does not match "
                             "the target network's param tree")
        node = nxt
    if not isinstance(node, dict) or parts[-1] not in node:
        raise ValueError(f"checkpoint param key {key!r} does not match "
                         "the target network's param tree")
    return node, parts[-1]


def _get_tree_item(tree, key: str):
    node, leaf = _parent_of(tree, key)
    return node[leaf]


def _set_tree_item(tree, key: str, value) -> None:
    node, leaf = _parent_of(tree, key)
    node[leaf] = value


def _leaf_blocks(leaf) -> Tuple[Optional[int], List[Tuple[int, np.ndarray]]]:
    """``(sharded_dim, [(start, host_block), ...])`` for the shards of one
    leaf THIS process holds, deduped across replica devices (tp/sp axes
    hold copies of the same block).  Replicated / host leaves yield
    ``(None, [(0, whole)])``.  Blocks are owned host copies — the next
    train step may donate the source buffers.

    The format indexes blocks by ONE sharded dim (the ZeRO-3 layout);
    a leaf partitioned over two or more axes (a TP ``param_rule``
    composed with dp) cannot be represented — refuse at save time
    rather than dedupe away the extra axis and commit a store every
    restore rejects."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        return None, [(0, np.array(leaf))]
    gshape = tuple(np.shape(leaf))
    dim = None
    blocks: Dict[int, np.ndarray] = {}
    for s in shards:
        bshape = tuple(np.shape(s.data))
        if dim is None and bshape != gshape:
            cut = [i for i, (b, g) in enumerate(zip(bshape, gshape))
                   if b != g]
            if len(cut) > 1:
                raise NotImplementedError(
                    f"save_sharded: leaf sharded over {len(cut)} axes "
                    f"(shard {bshape} of {gshape}) — the sharded "
                    "checkpoint format indexes one sharded dim per leaf "
                    "(the ZeRO-3 layout); save TP-sharded params through "
                    "the dense path")
            dim = cut[0]
        start = 0
        if dim is not None and len(s.index) > dim:
            start = int(s.index[dim].start or 0)
        if start not in blocks:
            blocks[start] = np.array(s.data)
    return dim, sorted(blocks.items())


class _ShardedSnapshot:
    """Host snapshot of a SHARDED network for ``save_sharded``: the model
    container is written param-less; each param / updater leaf is captured
    as this process's local shard blocks only — the global arrays never
    materialize on one host (the 1/dp memory story holds through the
    checkpoint path too).  RNG-neutral like :class:`_Snapshot`."""

    def __init__(self, net, process_index: int, process_count: int,
                 save_updater: bool = True):
        import jax
        self.net_class = type(net).__name__
        self.conf = net.conf
        self.params = {}            # model.zip carries conf+state only
        self.state = _host_copy(net.state)
        self.opt_state = None
        self.iteration = int(net.iteration)
        self.step = self.iteration
        self.epoch = int(net.epoch)
        self.rng, self.rng_typed = _rng_to_np(net._rng)
        pol = getattr(net, "shape_policy", None)
        self.shape_policy = pol.snapshot() if pol is not None else None
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        primary = self.process_index == 0
        mesh_desc = None
        topo_params: Dict[str, Any] = {}
        self.blocks: List[Tuple[str, str, Optional[int],
                                List[Tuple[int, np.ndarray]]]] = []
        for key, leaf in _tree_items(net.params):
            dim, blocks = _leaf_blocks(leaf)
            sh = getattr(leaf, "sharding", None)
            if mesh_desc is None and sh is not None and \
                    getattr(sh, "mesh", None) is not None:
                mesh_desc = {"axes": list(sh.mesh.axis_names),
                             "shape": [int(sh.mesh.shape[a])
                                       for a in sh.mesh.axis_names]}
            topo_params[key] = {"shape": [int(n) for n in np.shape(leaf)],
                                "dtype": str(np.dtype(leaf.dtype)),
                                "dim": dim}
            if dim is not None or primary:
                # replicated leaves are identical everywhere: only the
                # primary writes them (no process_count-fold duplication)
                self.blocks.append(("param", key, dim, blocks))
        topo_opt: List[Dict[str, Any]] = []
        opt_leaves = [] if (net.opt_state is None or not save_updater) \
            else jax.tree_util.tree_leaves(net.opt_state)
        for i, leaf in enumerate(opt_leaves):
            dim, blocks = _leaf_blocks(leaf)
            topo_opt.append({"shape": [int(n) for n in np.shape(leaf)],
                             "dtype": str(np.dtype(
                                 getattr(leaf, "dtype", np.asarray(leaf).dtype))),
                             "dim": dim})
            if dim is not None or primary:
                self.blocks.append(("opt", str(i), dim, blocks))
        self.topology = {"version": 1,
                         "process_count": self.process_count,
                         "mesh": mesh_desc,
                         "params": topo_params,
                         "opt": topo_opt}


class ShardBarrierError(RuntimeError):
    """A multi-writer barrier save round aborted: a writer was evicted
    mid-barrier or its block marker never landed within the budget.  The
    round's shared staging dir is left as a ``.tmp-`` orphan (discovery
    never sees it; ``sweep_orphans`` reclaims it) — the store's newest
    COMPLETE checkpoint is unchanged."""


@dataclass
class ShardBarrier:
    """Coordination contract for one multi-writer ``save_sharded`` round.

    Every process of a sharded world stages its ``shards-pNN.npz`` block
    into ONE shared staging directory — named deterministically from the
    step and the rendezvous ``generation``, so every writer of the same
    round agrees on it and a stale-generation writer (one that missed an
    eviction/admission) stages into a DIFFERENT directory no primary
    will ever commit.  After its block (and index) are durable, each
    writer posts a generation-fenced ``block-pNN.json`` marker; the
    primary commits manifest + rename only once every expected writer's
    marker has landed.

    - ``generation`` — the cluster view's rendezvous generation (0 for a
      static world): the fence tag baked into the staging-dir name and
      validated on every marker.
    - ``timeout_s`` — the primary's bounded barrier wait; expiry aborts
      the round with :class:`ShardBarrierError`.
    - ``policy`` — optional :class:`~.faults.RetryPolicy` whose seeded
      backoff paces the marker polls (``poll_s`` is the flat fallback).
    - ``live_fn`` — optional ``() -> collection of live writer ranks``;
      when a missing writer is no longer live (its lease expired — it
      was evicted mid-barrier) the round aborts immediately instead of
      waiting out the full timeout.
    """

    generation: int = 0
    timeout_s: float = 30.0
    poll_s: float = 0.05
    policy: Optional[Any] = None
    live_fn: Optional[Any] = None


class CheckpointManager:
    """Durable on-disk checkpoint store with atomic commits, checksum
    verification, retention, and background (double-buffered) saves.

    Retention knobs compose: the last ``keep_last`` checkpoints are always
    kept; checkpoints whose step is a multiple of ``keep_every_n`` are
    never deleted; with ``keep_best`` > 0, the best ``keep_best`` by
    recorded metric (``metric_mode``: "min" for losses, "max" for
    accuracies) are also pinned.
    """

    def __init__(self, directory: str, *, keep_last: int = 3,
                 keep_every_n: Optional[int] = None, keep_best: int = 0,
                 metric_mode: str = "min", background: bool = True,
                 save_updater: bool = True, registry=None):
        if metric_mode not in ("min", "max"):
            raise ValueError(f"metric_mode must be min|max, got {metric_mode}")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep_last = max(1, int(keep_last))
        self.keep_every_n = keep_every_n
        self.keep_best = int(keep_best)
        self.metric_mode = metric_mode
        self.background = background
        self.save_updater = save_updater
        self._registry = registry
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.last_error: Optional[BaseException] = None
        # test-only hook: seconds to sleep between staged file writes, so a
        # crash-consistency test can SIGKILL a saver subprocess mid-stage
        self._test_slow_s = float(os.environ.get(
            "DL4J_TPU_CKPT_TEST_SLOW_S", "0") or 0)
        # chaos-harness hook: a faults.ChaosSchedule attached here gets
        # on_commit_stage(step, stage) between staged file writes and may
        # hard-kill the process — proving the temp-then-rename protocol
        # leaves only an ignorable .tmp- orphan, never a torn checkpoint
        self.chaos = None

    # ------------------------------------------------------------- metrics
    def _reg(self):
        return self._registry if self._registry is not None \
            else default_registry()

    def _observe_write(self, seconds: float, nbytes: int, mode: str) -> None:
        reg = self._reg()
        if not reg.enabled:
            return
        reg.histogram("checkpoint_write_seconds",
                      "Wall time of one committed checkpoint write",
                      ("mode",), buckets=_WRITE_BUCKETS
                      ).labels(mode).observe(seconds)
        reg.histogram("checkpoint_bytes",
                      "Committed bytes per checkpoint",
                      buckets=_BYTES_BUCKETS).observe(nbytes)

    def _count_restore(self, result: str) -> None:
        reg = self._reg()
        if reg.enabled:
            reg.counter("checkpoint_restore_total",
                        "Checkpoint restore attempts by outcome",
                        ("result",)).labels(result).inc()

    # --------------------------------------------------------------- save
    def path_for(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{int(step):08d}")

    def save(self, net, *, cursor: Optional[Dict[str, int]] = None,
             metric: Optional[float] = None,
             blocking: Optional[bool] = None,
             step: Optional[int] = None) -> str:
        """Checkpoint ``net`` at its current iteration.  The snapshot is
        taken synchronously (host copies; RNG-neutral); the write runs on
        the background worker unless ``blocking`` (default: the manager's
        ``background`` flag inverted).  At most one write is in flight —
        a new save joins the previous one first.  ``step`` overrides the
        directory's step number (ElasticTrainer names checkpoints by its
        global data cursor, which can outrun a member's own optimizer
        iteration when it owns no batches in a window).  Returns the
        directory the checkpoint commits to."""
        snap = _Snapshot(net)
        if step is not None:
            snap.step = int(step)
        final = self.path_for(snap.step)
        if blocking is None:
            blocking = not self.background
        self.wait()                       # double-buffer: one in flight
        if blocking:
            self._write(snap, final, cursor, metric, mode="sync")
        else:
            t = threading.Thread(
                target=self._write_guarded,
                args=(snap, final, cursor, metric), daemon=False,
                name="dl4j-ckpt-writer")
            with self._lock:
                self._worker = t
            t.start()
        return final

    def save_sharded(self, net, *, cursor: Optional[Dict[str, int]] = None,
                     metric: Optional[float] = None,
                     blocking: Optional[bool] = None,
                     step: Optional[int] = None,
                     process_index: Optional[int] = None,
                     process_count: Optional[int] = None,
                     barrier: Optional[ShardBarrier] = None) -> str:
        """Shard-aware checkpoint of a mesh-sharded ``net`` (the ZeRO-3
        ``parallel.sharded.ShardedTrainer`` layout): the model container
        is written WITHOUT params, and every param/updater leaf is saved
        as this process's local shard blocks (``shards-pNN.npz`` + index)
        plus a ``topology.json`` manifest (mesh shape, per-leaf sharded
        dim, global shapes/dtypes).  The global arrays never materialize
        on one host.  Restore with :meth:`restore_sharded` — onto ANY
        mesh topology (portable resharding, arXiv:2112.01075).

        Multi-writer worlds (``process_count > 1``) MUST pass a
        :class:`ShardBarrier`: every process stages its block into the
        round's shared generation-fenced staging dir and posts a
        completion marker; non-primary writers return once their block
        is durable, and the primary commits manifest + rename only after
        every live writer's marker lands (bounded wait; an eviction or
        timeout aborts the round cleanly — see :class:`ShardBarrier`).
        Without a barrier a primary-only commit would record
        ``process_count`` shard files in topology.json but write ONE — a
        torn checkpoint every restore refuses; refuse up front."""
        import jax
        if process_index is None:
            process_index = jax.process_index()
        if process_count is None:
            process_count = jax.process_count()
        if (process_index != 0 or process_count > 1) and barrier is None:
            raise NotImplementedError(
                "multi-host save_sharded needs a staged-write barrier "
                "(every process's shard file must land before the "
                "primary commits) — pass barrier=ShardBarrier(...) or "
                "route multi-process saves through the elastic "
                "coordinator (ElasticTrainer over a ShardedTrainer)")
        snap = _ShardedSnapshot(net, process_index, process_count,
                                save_updater=self.save_updater)
        if step is not None:
            snap.step = int(step)
        final = self.path_for(snap.step)
        self.wait()                       # double-buffer: one in flight
        if barrier is not None:
            # barrier rounds are synchronous by construction: a
            # background writer racing the next round's markers would
            # tangle two generations in one staging dir
            self._write_sharded_barrier(snap, final, cursor, metric,
                                        barrier)
            return final
        if blocking is None:
            blocking = not self.background
        if blocking:
            self._write_sharded(snap, final, cursor, metric, mode="sync")
        else:
            t = threading.Thread(
                target=self._write_guarded,
                args=(snap, final, cursor, metric, self._write_sharded),
                daemon=False, name="dl4j-ckpt-writer")
            with self._lock:
                self._worker = t
            t.start()
        return final

    def wait(self) -> None:
        """Block until any in-flight background write commits."""
        with self._lock:
            t, self._worker = self._worker, None
        if t is not None:
            t.join()

    def _write_guarded(self, snap, final, cursor, metric,
                       writer=None) -> None:
        try:
            (writer or self._write)(snap, final, cursor, metric,
                                    mode="async")
        except Exception as e:
            self.last_error = e
            log.exception("background checkpoint to %s failed", final)

    def _write(self, snap: _Snapshot, final: str, cursor, metric,
               mode: str) -> None:
        from ..utils import model_serializer

        t0 = monotonic_s()
        with get_tracer().span("checkpoint.write", step=snap.iteration,
                               mode=mode):
            tmp = staging_dir(final)
            model_serializer.write_model(
                snap, os.path.join(tmp, "model.zip"),
                save_updater=self.save_updater)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                self.chaos.on_commit_stage(snap.step, 1)
            np.save(os.path.join(tmp, "rng.npy"), snap.rng)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                self.chaos.on_commit_stage(snap.step, 2)
            nbytes = self._finish_staging(tmp, final, snap, cursor, metric)
        self._observe_write(monotonic_s() - t0, nbytes, mode)
        try:
            self._apply_retention()
        except OSError:
            log.warning("checkpoint retention sweep failed in %s",
                        self.directory, exc_info=True)

    def _finish_staging(self, tmp: str, final: str, snap, cursor,
                        metric, sharded: bool = False,
                        pre_commit=None) -> int:
        """Write training_state.json + the checksum manifest into a staged
        checkpoint dir, then commit it with ONE rename.  Returns committed
        bytes.  Shared by the dense and sharded writers; ``pre_commit``
        (barrier path) runs between the manifest write and the rename —
        the crash-on-manifest probe window."""
        state = {
            "cursor": dict(cursor or {}),
            "iteration": snap.iteration,
            "epoch": snap.epoch,
            "rng_typed": bool(snap.rng_typed),
            "shape_policy": snap.shape_policy,
            "metric": None if metric is None else float(metric),
        }
        if sharded:
            state["sharded"] = True
        with open(os.path.join(tmp, "training_state.json"), "w",
                  encoding="utf-8") as f:
            json.dump(state, f, sort_keys=True, indent=1)
        files = manifest_for(tmp)
        nbytes = sum(int(v["bytes"]) for v in files.values())
        manifest = {"version": _MANIFEST_VERSION,
                    "step": snap.step, "epoch": snap.epoch,
                    "iteration": snap.iteration,
                    "metric": state["metric"],
                    "wall_time": time.time(),
                    "files": files}
        if sharded:
            manifest["sharded"] = True
        atomic_write_json(os.path.join(tmp, "manifest.json"), manifest)
        if pre_commit is not None:
            pre_commit()
        commit_dir(tmp, final)
        return nbytes

    def _write_sharded(self, snap: "_ShardedSnapshot", final: str, cursor,
                       metric, mode: str) -> None:
        from ..utils import model_serializer

        t0 = monotonic_s()
        with get_tracer().span("checkpoint.write_sharded",
                               step=snap.iteration, mode=mode):
            tmp = staging_dir(final)
            # param-less container: conf + replicated layer state + meta
            model_serializer.write_model(
                snap, os.path.join(tmp, "model.zip"), save_updater=False)
            np.save(os.path.join(tmp, "rng.npy"), snap.rng)
            atomic_write_json(os.path.join(tmp, "topology.json"),
                              snap.topology)
            # same crash-consistency probes as the dense writer: the slow
            # hook widens the staging window for SIGKILL tests, the chaos
            # stages hard-kill between staged writes (shards-after-
            # container and manifest-after-shards are the two torn-store
            # windows the temp-then-rename protocol must survive)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                self.chaos.on_commit_stage(snap.step, 1)
            self._write_shard_block(tmp, snap)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                self.chaos.on_commit_stage(snap.step, 2)
            nbytes = self._finish_staging(tmp, final, snap, cursor, metric,
                                          sharded=True)
        self._observe_write(monotonic_s() - t0, nbytes, mode)
        try:
            self._apply_retention()
        except OSError:
            log.warning("checkpoint retention sweep failed in %s",
                        self.directory, exc_info=True)

    @staticmethod
    def _write_shard_block(tmp: str, snap: "_ShardedSnapshot") -> None:
        """Write THIS process's shard blocks (``shards-pNN.npz``) and
        their index into a staging dir, fsynced — a completion marker
        posted after this returns only ever advertises durable bytes."""
        from .atomic import _fsync_path
        arrays: Dict[str, np.ndarray] = {}
        index: List[Dict[str, Any]] = []
        for kind, leaf_key, dim, blocks in snap.blocks:
            for start, block in blocks:
                name = f"b{len(index)}"
                arrays[name] = block
                index.append({"name": name, "kind": kind,
                              "leaf": leaf_key, "dim": dim,
                              "start": int(start)})
        pidx = snap.process_index
        npz = os.path.join(tmp, f"shards-p{pidx:02d}.npz")
        np.savez(npz, **arrays)
        _fsync_path(npz)
        atomic_write_json(os.path.join(tmp, f"shards-p{pidx:02d}.json"),
                          index)

    # ------------------------------------------------- multi-writer barrier
    def barrier_staging(self, final: str, generation: int) -> str:
        """The SHARED staging dir for one barrier round: deterministic
        from (step, generation) so every writer of the round agrees on
        it, ``.tmp-`` prefixed so discovery ignores it and orphan sweep
        reclaims an aborted round, and generation-fenced so a
        stale-generation writer stages into a directory no primary of a
        newer round will ever commit."""
        d, base = os.path.split(os.path.abspath(final))
        return os.path.join(d, f"{TMP_PREFIX}barrier-{base}-"
                               f"g{int(generation):06d}")

    @staticmethod
    def _scan_block_markers(tmp: str, generation: int) -> set:
        """Writer indices whose generation-matching completion marker has
        landed in ``tmp``.  A marker carrying a different generation is
        rejected (a stale writer handed the wrong barrier object can
        never satisfy a newer round's wait); a torn/unreadable marker is
        ignored (markers are atomic-rename writes, so this only races a
        concurrent sweep)."""
        have = set()
        try:
            names = os.listdir(tmp)
        except OSError:
            return have
        for name in names:
            m = _BLOCK_MARKER_RE.match(name)
            if not m:
                continue
            try:
                with open(os.path.join(tmp, name), encoding="utf-8") as f:
                    marker = json.load(f)
            except (OSError, ValueError):
                continue
            if int(marker.get("generation", -1)) != int(generation):
                log.warning("ignoring stale-generation block marker %s "
                            "(gen %s != round gen %d)", name,
                            marker.get("generation"), int(generation))
                continue
            have.add(int(m.group(1)))
        return have

    def _write_sharded_barrier(self, snap: "_ShardedSnapshot", final: str,
                               cursor, metric,
                               barrier: ShardBarrier) -> None:
        """One writer's side of the two-phase multi-writer commit.

        Phase 1 (every writer): stage this process's shard block into
        the round's shared staging dir, then post the generation-fenced
        ``block-pNN.json`` marker.  Non-primary writers return here —
        their block is durable and advertised.

        Phase 2 (primary only): write the param-less container + RNG +
        topology, wait — bounded, backoff-paced — for every expected
        writer's marker, then commit manifest + rename.  A writer
        evicted mid-barrier (``live_fn``) or a timeout aborts the round:
        the staging dir is left as a ``.tmp-`` orphan for sweep and
        :class:`ShardBarrierError` is raised — the store's newest
        complete checkpoint is untouched."""
        from ..utils import model_serializer

        t0 = monotonic_s()
        primary = snap.process_index == 0
        mode = "barrier-primary" if primary else "barrier"
        with get_tracer().span("checkpoint.write_sharded_barrier",
                               step=snap.iteration, mode=mode,
                               generation=int(barrier.generation)):
            tmp = self.barrier_staging(final, barrier.generation)
            os.makedirs(tmp, exist_ok=True)
            if primary:
                # param-less container + RNG + topology are the
                # primary's to stage (replicated state, identical on
                # every writer)
                model_serializer.write_model(
                    snap, os.path.join(tmp, "model.zip"),
                    save_updater=False)
                np.save(os.path.join(tmp, "rng.npy"), snap.rng)
                atomic_write_json(os.path.join(tmp, "topology.json"),
                                  snap.topology)
                if self._test_slow_s:
                    time.sleep(self._test_slow_s)
                if self.chaos is not None:
                    self.chaos.on_commit_stage(snap.step, 1)
            self._write_shard_block(tmp, snap)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                # stage 2 = "mid-block": the shard bytes are staged but
                # the completion marker is NOT posted — a writer killed
                # here never advertises, and the primary's barrier
                # aborts instead of committing its torn block
                self.chaos.on_commit_stage(snap.step, 2)
            atomic_write_json(
                os.path.join(tmp, f"block-p{snap.process_index:02d}.json"),
                {"process_index": int(snap.process_index),
                 "generation": int(barrier.generation),
                 "step": int(snap.step),
                 "complete": True})
            if not primary:
                self._observe_write(monotonic_s() - t0, 0, mode)
                return
            expected = set(range(snap.process_count))
            deadline = monotonic_s() + float(barrier.timeout_s)
            attempt = 0
            while True:
                have = self._scan_block_markers(tmp, barrier.generation)
                missing = sorted(expected - have)
                if not missing:
                    break
                if barrier.live_fn is not None:
                    try:
                        live = set(barrier.live_fn())
                    except Exception:
                        live = expected     # liveness unknown: keep waiting
                    dead = sorted(set(missing) - live)
                    if dead:
                        self._abort_barrier(
                            tmp, f"writer(s) {dead} evicted mid-barrier "
                                 f"(round generation {barrier.generation})")
                if monotonic_s() > deadline:
                    self._abort_barrier(
                        tmp, f"block marker(s) from writer(s) {missing} "
                             f"never landed within {barrier.timeout_s:.1f}s")
                attempt += 1
                if barrier.policy is not None:
                    barrier.policy.sleep(attempt,
                                         worker=snap.process_index)
                else:
                    time.sleep(barrier.poll_s)
            if self._test_slow_s:
                time.sleep(self._test_slow_s)
            if self.chaos is not None:
                # stage 3 = between barrier and commit: every block
                # landed, nothing committed — the primary dying here
                # must leave only the staging orphan
                self.chaos.on_commit_stage(snap.step, 3)
            nbytes = self._finish_staging(
                tmp, final, snap, cursor, metric, sharded=True,
                # stage 4 = after the manifest, before the rename — the
                # crash-on-manifest window
                pre_commit=(None if self.chaos is None else
                            lambda: self.chaos.on_commit_stage(
                                snap.step, 4)))
        self._observe_write(monotonic_s() - t0, nbytes, mode)
        try:
            self._apply_retention()
        except OSError:
            log.warning("checkpoint retention sweep failed in %s",
                        self.directory, exc_info=True)

    def _abort_barrier(self, tmp: str, detail: str):
        """Abort a barrier round: the shared staging dir stays behind as
        a ``.tmp-`` orphan (never a commit candidate; ``sweep_orphans``
        reclaims it once it ages past any in-flight round)."""
        reg = self._reg()
        if reg.enabled:
            reg.counter("checkpoint_barrier_aborts_total",
                        "Multi-writer sharded save rounds aborted before "
                        "commit").inc()
        log.warning("sharded barrier save aborted: %s (staging %s left "
                    "for orphan sweep)", detail, tmp)
        raise ShardBarrierError(f"sharded barrier save aborted: {detail}")

    # ---------------------------------------------------------- discovery
    @staticmethod
    def validate(path: str) -> Dict[str, Any]:
        """Verify a checkpoint directory: manifest present and parseable,
        every listed file present with a matching SHA-256.  Returns the
        manifest; raises :class:`CorruptCheckpointError` otherwise."""
        mpath = os.path.join(path, "manifest.json")
        if not os.path.isfile(mpath):
            raise CorruptCheckpointError(path, "manifest.json missing "
                                               "(uncommitted or partial)")
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except ValueError as e:
            raise CorruptCheckpointError(path, f"manifest unreadable: {e}")
        files = manifest.get("files")
        if not isinstance(files, dict) or not files:
            raise CorruptCheckpointError(path, "manifest lists no files")
        for name, want in files.items():
            fpath = os.path.join(path, name)
            if not os.path.isfile(fpath):
                raise CorruptCheckpointError(path, f"{name} missing")
            if os.path.getsize(fpath) != int(want["bytes"]):
                raise CorruptCheckpointError(
                    path, f"{name}: size {os.path.getsize(fpath)} != "
                          f"manifest {want['bytes']}")
            got = sha256_file(fpath)
            if got != want["sha256"]:
                raise CorruptCheckpointError(
                    path, f"{name}: checksum mismatch "
                          f"({got[:12]}… != {want['sha256'][:12]}…)")
        return manifest

    def checkpoints(self, validate: bool = True
                    ) -> List[Tuple[int, str, Dict[str, Any]]]:
        """All valid checkpoints, ascending by step: ``(step, path,
        manifest)``.  Partial/corrupt directories are skipped with a
        warning (and counted) instead of raising."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in sorted(names):
            m = _CKPT_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.directory, name)
            if not os.path.isdir(path):
                continue
            try:
                manifest = self.validate(path) if validate else {}
            except CorruptCheckpointError as e:
                log.warning("skipping corrupt checkpoint: %s", e)
                self._count_restore("skipped")
                continue
            out.append((int(m.group(1)), path, manifest))
        return out

    def latest(self) -> Optional[str]:
        """Path of the newest VALID checkpoint, or None.  ``.tmp-`` staging
        orphans and checksum-corrupt directories are never candidates."""
        ckpts = self.checkpoints()
        return ckpts[-1][1] if ckpts else None

    def latest_complete(self, after_step: int = -1, kind: str = "any"
                        ) -> Optional[Tuple[int, str]]:
        """Newest manifest-verified checkpoint strictly newer than
        ``after_step``: ``(step, path)`` or None.  The serving tier's
        train→serve promotion poll: a watcher holding the step it already
        serves asks "is there anything newer and COMPLETE?" — corrupt or
        still-staging directories never answer yes.  A corrupt shard
        file fails its manifest checksum like any other file, so a torn
        sharded dir is skipped the same way.

        ``kind`` filters by layout: ``"any"`` (default), ``"dense"``
        (restorable with :meth:`restore`) or ``"sharded"`` (restorable
        with :meth:`restore_sharded`) — a consumer wired to one restore
        path can ask only for checkpoints it can actually load."""
        if kind not in ("any", "dense", "sharded"):
            raise ValueError(f"kind must be any|dense|sharded, got {kind!r}")
        for step, path, manifest in reversed(self.checkpoints()):
            if step <= int(after_step):
                break
            sharded = bool(manifest.get("sharded"))
            if kind == "dense" and sharded:
                continue
            if kind == "sharded" and not sharded:
                continue
            return step, path
        return None

    def sweep_orphans(self, min_age_s: float = 0.0) -> int:
        """Remove ``.tmp-`` staging leftovers from crashed writers.
        ``min_age_s`` spares young staging dirs — a peer's in-flight
        barrier round must not be reclaimed from under its writers."""
        from .atomic import discard_orphans
        return discard_orphans(
            self.directory, min_age_s=min_age_s,
            log_warning=lambda p: log.warning(
                "removing crashed checkpoint staging dir %s", p))

    # ----------------------------------------------------------- restore
    def restore_any(self, path: Optional[str] = None, net=None, *,
                    mesh=None, min_shard_size: Optional[int] = None,
                    load_updater: bool = True):
        """Restore a checkpoint of EITHER layout: a sharded dir
        (``topology.json`` present) routes through
        :meth:`restore_sharded` (``mesh``/``min_shard_size`` apply
        there; ``mesh=None`` leaves leaves host-placed), a dense dir
        through :meth:`restore`.  The single place the store's layout
        sniff lives — consumers that must promote or resume whatever
        the training tier wrote (serving promotion, elastic restart)
        call this instead of re-implementing the detection."""
        if path is None:
            path = self.latest()
            if path is None:
                raise FileNotFoundError(
                    f"no valid checkpoint found in {self.directory}")
        if os.path.isfile(os.path.join(path, "topology.json")):
            kw: Dict[str, Any] = {"mesh": mesh,
                                  "load_updater": load_updater}
            if min_shard_size is not None:
                kw["min_shard_size"] = min_shard_size
            return self.restore_sharded(path=path, net=net, **kw)
        return self.restore(path=path, net=net, load_updater=load_updater)

    def restore(self, path: Optional[str] = None, net=None,
                load_updater: bool = True):
        """Restore from ``path`` (default: ``latest()``).  With ``net``
        given, state is loaded INTO it (must match the saved topology);
        otherwise a fresh network is built from the saved configuration.
        Returns ``(net, training_state)`` where ``training_state`` carries
        the resume cursor.  Refuses partial/corrupt checkpoints with
        :class:`CorruptCheckpointError`."""
        from ..utils import model_serializer

        if path is None:
            path = self.latest()
            if path is None:
                raise FileNotFoundError(
                    f"no valid checkpoint found in {self.directory}")
        try:
            self.validate(path)
        except CorruptCheckpointError:
            self._count_restore("corrupt")
            raise
        if os.path.isfile(os.path.join(path, "topology.json")):
            raise ValueError(
                f"{path} is a SHARDED checkpoint (its model container "
                "carries no params) — use restore_sharded()")
        if net is None:
            net = model_serializer.restore_model(
                os.path.join(path, "model.zip"), load_updater=load_updater)
        else:
            model_serializer.load_into(
                net, os.path.join(path, "model.zip"),
                load_updater=load_updater)
        state = _read_training_state(path)
        _apply_training_state(net, state)
        _apply_rng(net, path, state)
        self._count_restore("ok")
        return net, state

    def restore_sharded(self, path: Optional[str] = None, net=None, *,
                        mesh=None, min_shard_size: Optional[int] = None,
                        load_updater: bool = True):
        """Restore a :meth:`save_sharded` checkpoint, RESHARDING onto any
        mesh topology: shard blocks from every process file are
        reassembled host-side into global leaves, then re-placed with the
        ZeRO-3 layout rule for ``mesh``'s data-axis size — a dp=4
        checkpoint restores onto a dp=2 or dp=8 mesh with bitwise-equal
        global params (reassembly and re-placement move bytes, never
        arithmetic).  This is also the elastic-rejoin path for sharded
        models: the surviving world size just becomes the new mesh.

        ``mesh=None`` leaves the restored leaves unsharded on the default
        device (wrap in a ``ShardedTrainer`` to place them later).
        ``min_shard_size`` feeds the layout rule (default: the trainer's
        default threshold).  Returns ``(net, training_state)`` like
        :meth:`restore`.  Refuses partial/corrupt checkpoints — a shard
        file failing its manifest checksum raises
        :class:`CorruptCheckpointError`."""
        import jax
        import jax.numpy as jnp

        from ..parallel.mesh import (DATA_AXIS, DEFAULT_MIN_SHARD_SIZE,
                                     place_sharded, zero3_spec)
        from ..utils import model_serializer

        if path is None:
            path = self.latest()
            if path is None:
                raise FileNotFoundError(
                    f"no valid checkpoint found in {self.directory}")
        try:
            self.validate(path)
        except CorruptCheckpointError:
            self._count_restore("corrupt")
            raise
        tpath = os.path.join(path, "topology.json")
        if not os.path.isfile(tpath):
            raise ValueError(
                f"{path} is not a sharded checkpoint (no topology.json) — "
                "use restore()")
        with open(tpath, encoding="utf-8") as f:
            topo = json.load(f)

        # ---- gather every process's blocks ---------------------------
        shard_files = sorted(n for n in os.listdir(path)
                             if _SHARD_FILE_RE.match(n))
        want = int(topo.get("process_count", 1))
        if len(shard_files) != want:
            self._count_restore("corrupt")
            raise CorruptCheckpointError(
                path, f"expected {want} shard file(s), found "
                      f"{len(shard_files)}")
        blocks: Dict[Tuple[str, str], List[Tuple[int, np.ndarray]]] = {}
        dims: Dict[Tuple[str, str], Optional[int]] = {}
        for fname in shard_files:
            ipath = os.path.join(path, fname[:-len(".npz")] + ".json")
            if not os.path.isfile(ipath):
                self._count_restore("corrupt")
                raise CorruptCheckpointError(path, f"{fname} has no index")
            try:
                with open(ipath, encoding="utf-8") as f:
                    index = json.load(f)
                with np.load(os.path.join(path, fname)) as z:
                    for entry in index:
                        k = (entry["kind"], entry["leaf"])
                        dims[k] = entry["dim"]
                        bl = blocks.setdefault(k, [])
                        start = int(entry["start"])
                        if all(s != start for s, _ in bl):
                            bl.append((start, z[entry["name"]]))
            except (ValueError, KeyError, OSError) as e:
                # checksums passed, so this is a writer bug, not bit rot
                # — still refuse with the store-level error the callers
                # (ElasticTrainer fallback, promotion skip) understand
                self._count_restore("corrupt")
                raise CorruptCheckpointError(
                    path, f"{fname} unreadable: {type(e).__name__}: {e}")

        def assemble(kind: str, leaf_key: str, spec: Dict[str, Any]):
            k = (kind, leaf_key)
            if k not in blocks:
                self._count_restore("corrupt")
                raise CorruptCheckpointError(
                    path, f"no shard blocks for {kind} leaf {leaf_key}")
            dim = dims[k]
            parts = sorted(blocks[k])
            arr = parts[0][1] if dim is None else np.concatenate(
                [b for _, b in parts], axis=dim)
            if list(arr.shape) != list(spec["shape"]):
                self._count_restore("corrupt")
                raise CorruptCheckpointError(
                    path, f"{kind} leaf {leaf_key}: reassembled shape "
                          f"{list(arr.shape)} != manifest {spec['shape']}")
            return arr

        # ---- the target network --------------------------------------
        mzip = os.path.join(path, "model.zip")
        if net is None:
            net = model_serializer.restore_model(mzip, load_updater=False)
        else:
            model_serializer.load_into(net, mzip, load_updater=False)

        # ---- re-placement under the NEW topology ---------------------
        ms = DEFAULT_MIN_SHARD_SIZE if min_shard_size is None \
            else int(min_shard_size)
        if mesh is not None:
            from jax.sharding import NamedSharding
            dp = mesh.shape.get(DATA_AXIS, 1)

            def place(arr):
                return place_sharded(arr, NamedSharding(
                    mesh, zero3_spec(arr.shape, dp, ms)))
        else:
            place = jnp.asarray

        # stage EVERYTHING (params and updater) before touching the net,
        # then swap in one block — a mid-restore mismatch (renamed layer,
        # wrong shapes, different updater config) must never leave a
        # caller's live net half old, half new
        staged = _copy_dict_tree(net.params)
        for key, spec in topo.get("params", {}).items():
            cur = _get_tree_item(staged, key)
            if list(np.shape(cur)) != list(spec["shape"]):
                raise ValueError(
                    f"checkpoint param {key!r} has shape {spec['shape']} "
                    f"but the target network's is {list(np.shape(cur))} — "
                    "topology mismatch")
            arr = assemble("param", key, spec)
            _set_tree_item(staged, key, place(arr))
        opt_specs = topo.get("opt") or []
        staged_opt = None
        if load_updater and opt_specs:
            if net._tx is None:
                net._tx = net._build_tx()
            template = net.opt_state if net.opt_state is not None \
                else net._tx.init(net.params)
            treedef = jax.tree_util.tree_structure(template)
            fresh = jax.tree_util.tree_leaves(template)
            if len(fresh) != len(opt_specs):
                raise ValueError(
                    f"updater state mismatch: saved {len(opt_specs)} "
                    f"leaves, model needs {len(fresh)}")
            staged_opt = jax.tree_util.tree_unflatten(
                treedef, [place(assemble("opt", str(i), spec))
                          for i, spec in enumerate(opt_specs)])
        net.params = staged
        if staged_opt is not None:
            net.opt_state = staged_opt
        state = _read_training_state(path)
        _apply_training_state(net, state)
        _apply_rng(net, path, state)
        self._count_restore("ok")
        return net, state

    # --------------------------------------------------------- retention
    def _apply_retention(self) -> None:
        ckpts = self.checkpoints(validate=False)
        if len(ckpts) <= self.keep_last:
            return
        keep = {step for step, _, _ in ckpts[-self.keep_last:]}
        if self.keep_every_n:
            keep |= {step for step, _, _ in ckpts
                     if step % int(self.keep_every_n) == 0}
        if self.keep_best > 0:
            scored = []
            for step, p, _ in ckpts:
                try:
                    metric = _read_training_state(p).get("metric")
                except (OSError, ValueError):
                    metric = None
                if metric is not None:
                    scored.append((float(metric), step))
            scored.sort(reverse=(self.metric_mode == "max"))
            keep |= {step for _, step in scored[:self.keep_best]}
        for step, p, _ in ckpts:
            if step not in keep:
                shutil.rmtree(p, ignore_errors=True)


def _read_training_state(path: str) -> Dict[str, Any]:
    sp = os.path.join(path, "training_state.json")
    if not os.path.isfile(sp):
        return {}
    with open(sp, encoding="utf-8") as f:
        return json.load(f)


def _apply_training_state(net, state: Dict[str, Any]) -> None:
    """Apply the non-model training state onto a restored network:
    ShapePolicy bucket history — padding decisions, and therefore compiled
    shapes, must match the pre-interruption run on resume."""
    pol_snap = state.get("shape_policy")
    if pol_snap and getattr(net, "shape_policy", None) is not None:
        net.shape_policy.restore_state(pol_snap)


def _apply_rng(net, path: str, state: Dict[str, Any]) -> None:
    rp = os.path.join(path, "rng.npy")
    if os.path.isfile(rp):
        net._rng = _np_to_rng(np.load(rp), bool(state.get("rng_typed")))


@dataclass
class CheckpointConfig:
    """Declarative checkpointing for ``fit``/``fit_on_device``:

    - ``directory`` or a prebuilt ``manager``;
    - save triggers: every N optimizer iterations and/or every N epochs
      (epoch-boundary saves always fire in ``fit_on_device``'s per-epoch
      path);
    - retention: ``keep_last`` / ``keep_every_n`` / ``keep_best`` (+
      ``metric_mode``);
    - ``background``: write off-thread (the train loop only pays the host
      snapshot);
    - ``save_on_preempt``: install a SIGTERM hook for the duration of the
      fit — a preemption notice triggers one final synchronous save at the
      next iteration boundary, then fit returns cleanly.
    """

    directory: Optional[str] = None
    manager: Optional[CheckpointManager] = None
    save_every_n_iterations: Optional[int] = None
    save_every_n_epochs: Optional[int] = None
    keep_last: int = 3
    keep_every_n: Optional[int] = None
    keep_best: int = 0
    metric_mode: str = "min"
    background: bool = True
    save_on_preempt: bool = False
    save_updater: bool = True
    _resolved: Optional[CheckpointManager] = field(
        default=None, repr=False, compare=False)

    def resolve(self) -> CheckpointManager:
        if self._resolved is None:
            if self.manager is not None:
                self._resolved = self.manager
            elif self.directory:
                self._resolved = CheckpointManager(
                    self.directory, keep_last=self.keep_last,
                    keep_every_n=self.keep_every_n,
                    keep_best=self.keep_best, metric_mode=self.metric_mode,
                    background=self.background,
                    save_updater=self.save_updater)
            else:
                raise ValueError(
                    "CheckpointConfig needs a directory or a manager")
        return self._resolved


def resume_network(net, resume_from, load_updater: bool = True
                   ) -> Dict[str, Any]:
    """Restore checkpoint state INTO ``net`` and return the training
    state (with the resume cursor).  ``resume_from`` may be:

    - a :class:`CheckpointManager` or :class:`CheckpointConfig` (latest
      valid checkpoint in its store);
    - a checkpoint directory (``.../ckpt-00000042``);
    - a store directory containing ``ckpt-*`` entries (latest is used);
    - a bare model zip (model only — cursor resets to zero).
    """
    from ..utils import model_serializer

    if isinstance(resume_from, CheckpointConfig):
        resume_from = resume_from.resolve()
    if isinstance(resume_from, CheckpointManager):
        _, state = resume_from.restore(net=net, load_updater=load_updater)
        return state
    path = str(resume_from)
    if os.path.isdir(path):
        if os.path.isfile(os.path.join(path, "manifest.json")):
            mgr = CheckpointManager(os.path.dirname(path) or ".",
                                    background=False)
            _, state = mgr.restore(path=path, net=net,
                                   load_updater=load_updater)
            return state
        mgr = CheckpointManager(path, background=False)
        _, state = mgr.restore(net=net, load_updater=load_updater)
        return state
    # bare model container
    model_serializer.load_into(net, path, load_updater=load_updater)
    return {}


class FitCheckpointer:
    """Drives a :class:`CheckpointConfig` inside a network's fit loop:
    resume-cursor bookkeeping, iteration/epoch save triggers, and the
    optional SIGTERM save-on-preempt hook.  Built by ``fit`` when either
    ``checkpoint=`` or ``resume_from=`` is passed."""

    def __init__(self, net, config: Optional[CheckpointConfig],
                 resume_from=None):
        self.net = net
        self.config = config
        self.manager = config.resolve() if config is not None else None
        state = resume_network(net, resume_from) \
            if resume_from is not None else {}
        cursor = state.get("cursor") or {}
        self.start_epoch = int(cursor.get("fit_epoch", 0))
        self.skip_batches = int(cursor.get("batch_seq", 0))
        self._last_saved_iter = int(net.iteration)
        self._preempted = False
        self._old_handler = None
        self.preempt_saved: Optional[str] = None
        # set by the fit loop's _StepForensics: flushes buffered step
        # records into the flight recorder before a preemption dump
        self.pre_dump = None
        if self.manager is not None and config.save_on_preempt:
            import signal
            try:
                self._old_handler = signal.signal(signal.SIGTERM,
                                                  self._on_sigterm)
            except ValueError:
                # signal handlers only install from the main thread
                self._old_handler = None

    def _on_sigterm(self, signum, frame):
        self._preempted = True

    def _dump_preempt(self) -> None:
        """Commit the flight-recorder window next to the preemption
        checkpoint: the final-seconds forensics (recent steps, spans,
        metric snapshots) that explain what the run was doing when the
        scheduler pulled it.  Best-effort — the preemption save itself
        must never be jeopardized by a forensics write."""
        from ..observability.recorder import get_flight_recorder
        rec = get_flight_recorder()
        if rec is None or not rec.enabled:
            return
        try:
            if self.pre_dump is not None:
                self.pre_dump()   # drain buffered step records first
            rec.record("train", "preempted", saved=self.preempt_saved,
                       iteration=int(self.net.iteration))
            rec.dump("preempt", directory=self.manager.directory)
        except Exception:
            pass

    def _save(self, fit_epoch: int, batch_seq: int,
              blocking: bool = False) -> str:
        metric = None
        try:
            metric = float(self.net._score)
        except Exception:
            pass
        path = self.manager.save(
            self.net, cursor={"fit_epoch": fit_epoch,
                              "batch_seq": batch_seq},
            metric=metric, blocking=True if blocking else None)
        self._last_saved_iter = int(self.net.iteration)
        return path

    def due(self) -> bool:
        """Would :meth:`after_batch` save right now?  Side-effect-free
        preview for the pipelined fit loop (ISSUE 18): a checkpoint
        boundary must drain the bounded dispatch window BEFORE the save
        runs, so the checkpoint captures a fully materialized step and
        mid-window resume stays digest-exact."""
        if self.manager is None:
            return False
        n = self.config.save_every_n_iterations
        return bool(self._preempted or (
            n and int(self.net.iteration) - self._last_saved_iter >= n))

    def after_batch(self, fit_epoch: int, batch_seq: int) -> bool:
        """Call after each fitted batch (``batch_seq`` = batches consumed
        so far this epoch).  Saves on the iteration trigger; returns True
        when a SIGTERM was received — one final synchronous save has been
        taken and fit should return."""
        if self.manager is None:
            return False
        n = self.config.save_every_n_iterations
        if n and int(self.net.iteration) - self._last_saved_iter >= n:
            self._save(fit_epoch, batch_seq)
        if self._preempted:
            self.preempt_saved = self._save(fit_epoch, batch_seq,
                                            blocking=True)
            self._dump_preempt()
            return True
        return False

    def after_epoch(self, fit_epoch: int) -> bool:
        """Call after each completed epoch; saves on the epoch trigger
        with a cursor pointing at the next epoch's start.  An
        iteration-count trigger also fires here when enough optimizer
        steps accumulated since the last save — the hook
        ``fit_on_device``'s epoch-granular path relies on (its iterations
        advance by a whole epoch per dispatch)."""
        if self.manager is None:
            return False
        n = self.config.save_every_n_epochs
        ni = self.config.save_every_n_iterations
        if (n and (fit_epoch + 1) % n == 0) or \
                (ni and int(self.net.iteration) - self._last_saved_iter
                 >= ni):
            self._save(fit_epoch + 1, 0)
        if self._preempted:
            self.preempt_saved = self._save(fit_epoch + 1, 0, blocking=True)
            self._dump_preempt()
            return True
        return False

    def close(self) -> None:
        """Restore the SIGTERM handler and join any in-flight write."""
        if self._old_handler is not None:
            import signal
            try:
                signal.signal(signal.SIGTERM, self._old_handler)
            except ValueError:
                pass
            self._old_handler = None
        if self.manager is not None:
            self.manager.wait()
