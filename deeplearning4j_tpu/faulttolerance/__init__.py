"""Fault tolerance: crash-consistent checkpoints, exact resume, worker
recovery.

Three pillars (TensorFlow's production posture — PAPERS.md 1605.08695:
cheap periodic checkpointing + automatic recovery, not per-op
reliability):

- :mod:`.atomic` — temp-then-rename commits with fsync + per-file
  checksums: the single write path for durable state (model zips,
  checkpoint directories); graftlint JX014 flags bypasses.
- :mod:`.checkpoint` — :class:`CheckpointManager` (durable store:
  manifest checksums, background double-buffered saves, retention,
  corrupt-checkpoint skipping) and the ``fit(checkpoint=, resume_from=)``
  integration for exact preemption-safe resume.
- :mod:`.faults` — :class:`FaultInjector` (seeded, deterministic worker
  fault harness), :class:`RetryPolicy` (exponential backoff + jitter,
  per-worker seeded streams) behind the training masters' retry /
  straggler-timeout / elastic degradation machinery, and the
  process-level chaos harness (:class:`ChaosSchedule` /
  :class:`ChaosBroker`: seeded SIGKILLs, broker-link partitions,
  mid-commit crashes).
- :mod:`.cluster` — lease-based elastic membership over the shared
  checkpoint store: :class:`FileLeaseStore`, :class:`ClusterMember`
  heartbeats, :class:`ClusterCoordinator` (eviction, round-boundary
  admission, rendezvous generation fencing).
"""
from .atomic import atomic_file, atomic_write_bytes, atomic_write_json
from .checkpoint import (CheckpointConfig, CheckpointManager,
                         CorruptCheckpointError, FitCheckpointer,
                         ShardBarrier, ShardBarrierError, resume_network)
from .cluster import (ClusterCoordinator, ClusterMember, ClusterView,
                      FileLeaseStore, live_ranks, shard_owner)
from .faults import (ChaosBroker, ChaosSchedule, FaultInjector,
                     InjectedWorkerFault, RetryPolicy)

__all__ = ["atomic_file", "atomic_write_bytes", "atomic_write_json",
           "CheckpointConfig", "CheckpointManager", "CorruptCheckpointError",
           "FitCheckpointer", "ShardBarrier", "ShardBarrierError",
           "resume_network",
           "ClusterCoordinator", "ClusterMember", "ClusterView",
           "FileLeaseStore", "live_ranks", "shard_owner",
           "ChaosBroker", "ChaosSchedule",
           "FaultInjector", "InjectedWorkerFault", "RetryPolicy"]
