"""deeplearning4j_tpu — a TPU-native deep-learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of Deeplearning4j
(reference repo surveyed in SURVEY.md).  User surface mirrors the reference's
config-driven API (NeuralNetConfiguration builder → MultiLayerNetwork /
ComputationGraph) while the execution model is idiomatic TPU: one jitted XLA
program per train step, pytree params, mesh-sharded scale-out.
"""

__version__ = "0.1.0"

from . import observability
from .nn.compile_cache import (persistent_cache_status,
                               wire_persistent_cache)

# opt-in persistent XLA compile cache: DL4J_TPU_COMPILE_CACHE=<dir> makes
# process restarts reload compiled executables from disk instead of
# recompiling (no env var -> no-op).  Best-effort: a jax version without
# the cache flags must not break package import.
try:
    wire_persistent_cache()
except Exception:  # noqa: BLE001 - import must survive any cache failure
    pass

from .nn.conf.input_type import InputType
from .nn.conf.multi_layer import (MultiLayerConfiguration,
                                  NeuralNetConfiguration)
from .nn.conf.computation_graph import ComputationGraphConfiguration
from .nn.computation_graph import ComputationGraph
from .nn.multilayer import MultiLayerNetwork
from .nn.precision import PrecisionPolicy

__all__ = [
    "ComputationGraph",
    "ComputationGraphConfiguration",
    "InputType",
    "MultiLayerConfiguration",
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
    "PrecisionPolicy",
    "observability",
    "persistent_cache_status",
    "wire_persistent_cache",
]
