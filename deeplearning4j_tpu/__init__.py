"""deeplearning4j_tpu — a TPU-native deep-learning framework.

Brand-new JAX/XLA/Pallas re-design with the capabilities of Deeplearning4j
(reference repo surveyed in SURVEY.md).  User surface mirrors the reference's
config-driven API (NeuralNetConfiguration builder → MultiLayerNetwork /
ComputationGraph) while the execution model is idiomatic TPU: one jitted XLA
program per train step, pytree params, mesh-sharded scale-out.
"""

__version__ = "0.1.0"

from . import observability
from .nn.conf.input_type import InputType
from .nn.conf.multi_layer import (MultiLayerConfiguration,
                                  NeuralNetConfiguration)
from .nn.conf.computation_graph import ComputationGraphConfiguration
from .nn.computation_graph import ComputationGraph
from .nn.multilayer import MultiLayerNetwork

__all__ = [
    "ComputationGraph",
    "ComputationGraphConfiguration",
    "InputType",
    "MultiLayerConfiguration",
    "NeuralNetConfiguration",
    "MultiLayerNetwork",
    "observability",
]
