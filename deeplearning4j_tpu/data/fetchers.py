"""Additional dataset fetchers/iterators (reference
``deeplearning4j-core/.../datasets/fetchers/``: ``EmnistDataFetcher``,
``CifarDataSetIterator`` (DataVec image pipeline), ``TinyImageNetFetcher``).

Same gating pattern as MNIST (``mnist.py``): real corpus read from a local
cache dir when present (this environment has no egress — the reference's
checksum download is replaced by env-var paths), deterministic synthetic
drop-in with identical shapes otherwise.
"""
from __future__ import annotations

import os
import zlib
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .dataset import INDArrayDataSetIterator
from .mnist import _read_idx

__all__ = ["EmnistDataSetIterator", "CifarDataSetIterator",
           "TinyImageNetDataSetIterator"]

# EMNIST splits -> (n_classes, idx file prefix)
_EMNIST_VARIANTS = {
    "byclass": 62, "bymerge": 47, "balanced": 47, "letters": 26,
    "digits": 10, "mnist": 10,
}


def _synthetic_images(n: int, hw: int, channels: int, n_classes: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Class-dependent bright patches + noise (learnable, deterministic)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, n)
    shape = (n, hw, hw, channels) if channels > 1 else (n, hw, hw)
    images = (rng.standard_normal(shape) * 16 + 32).clip(0, 255)
    cell = max(hw // 8, 2)
    per_row = max(hw // cell - 1, 1)
    for c in range(n_classes):
        r, col = divmod(c % (per_row * per_row), per_row)
        m = labels == c
        sl = (m, slice(r * cell, (r + 2) * cell),
              slice(col * cell, (col + 2) * cell))
        images[sl] += 120 + 40 * ((c // (per_row * per_row)) % 3)
    return images.clip(0, 255).astype(np.uint8), labels.astype(np.int64)


class EmnistDataSetIterator(INDArrayDataSetIterator):
    """EMNIST (reference ``EmnistDataSetIterator.java``): IDX files from
    ``EMNIST_DIR`` (e.g. emnist-letters-train-images-idx3-ubyte) or synthetic.
    ``dataset`` selects the split; labels are 0-based one-hot."""

    def __init__(self, dataset: str, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 6):
        if dataset not in _EMNIST_VARIANTS:
            raise ValueError(f"unknown EMNIST split '{dataset}'; expected one "
                             f"of {sorted(_EMNIST_VARIANTS)}")
        self.dataset = dataset
        n_classes = _EMNIST_VARIANTS[dataset]
        data = self._load_real(dataset, train)
        self.synthetic = data is None
        if data is None:
            # crc32, not hash(): hash() is salted per process, which would
            # give distributed workers different "deterministic" data
            images, labels = _synthetic_images(
                4096 if train else 1024, 28, 1, n_classes,
                seed=zlib.crc32(dataset.encode()) % 2**31
                + (0 if train else 1))
        else:
            images, labels = data
            labels = labels.astype(np.int64)
            if dataset == "letters" and labels.min() == 1:
                labels = labels - 1  # letters split is 1-based in the corpus
        feats = images.astype(np.float32).reshape(len(images), -1) / 255.0
        onehot = np.eye(n_classes, dtype=np.float32)[labels]
        super().__init__(feats, onehot, batch_size, shuffle=shuffle, seed=seed)

    @staticmethod
    def _load_real(dataset: str, train: bool):
        d = os.environ.get("EMNIST_DIR")
        if not d or not Path(d).expanduser().is_dir():
            return None
        d = Path(d).expanduser()
        part = "train" if train else "test"
        img = d / f"emnist-{dataset}-{part}-images-idx3-ubyte"
        lbl = d / f"emnist-{dataset}-{part}-labels-idx1-ubyte"
        for p in (img, lbl):
            if not (p.exists() or p.with_suffix(p.suffix + ".gz").exists()):
                return None
        gz = lambda p: p if p.exists() else p.with_suffix(p.suffix + ".gz")
        return _read_idx(gz(img)), _read_idx(gz(lbl))

    @staticmethod
    def num_labels(dataset: str) -> int:
        return _EMNIST_VARIANTS[dataset]


class CifarDataSetIterator(INDArrayDataSetIterator):
    """CIFAR-10 (reference ``CifarDataSetIterator.java``): reads the binary
    batches (3073-byte records: label + 3x32x32 CHW) from ``CIFAR_DIR``,
    synthetic otherwise.  Features NHWC [n,32,32,3] in [0,1]."""

    N_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, shuffle: bool = True,
                 seed: int = 6):
        data = self._load_real(train)
        self.synthetic = data is None
        if data is None:
            images, labels = _synthetic_images(
                4096 if train else 1024, 32, 3, self.N_CLASSES,
                seed=99 if train else 100)
        else:
            images, labels = data
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        if images.dtype == np.float32:  # real corpus: already scaled by decode
            feats = images
        else:
            feats = images.astype(np.float32) / 255.0
        onehot = np.eye(self.N_CLASSES, dtype=np.float32)[labels]
        super().__init__(feats, onehot, batch_size, shuffle=shuffle, seed=seed)

    @staticmethod
    def _load_real(train: bool):
        d = os.environ.get("CIFAR_DIR")
        if not d or not Path(d).expanduser().is_dir():
            return None
        d = Path(d).expanduser()
        files = ([d / f"data_batch_{i}.bin" for i in range(1, 6)]
                 if train else [d / "test_batch.bin"])
        if not all(f.exists() for f in files):
            return None
        from ..utils.native import decode_cifar
        images, labels = [], []
        for f in files:
            # native C++ decode (GIL-free CHW->NHWC transpose + 1/255 scale);
            # already float32 in [0,1], so __init__ skips its own rescale
            lab, img = decode_cifar(f.read_bytes())
            labels.append(lab.astype(np.int64))
            images.append(img)
        return np.concatenate(images), np.concatenate(labels)


class TinyImageNetDataSetIterator(INDArrayDataSetIterator):
    """TinyImageNet-200 (reference ``TinyImageNetFetcher.java``): 64x64x3,
    200 classes, read from the standard extracted layout under
    ``TINY_IMAGENET_DIR`` (train/<wnid>/images/*.JPEG), synthetic otherwise."""

    N_CLASSES = 200
    HW = 64

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, shuffle: bool = True,
                 seed: int = 6):
        data = self._load_real(train, num_examples)
        self.synthetic = data is None
        if data is None:
            n = num_examples or (2048 if train else 512)
            images, labels = _synthetic_images(
                n, self.HW, 3, self.N_CLASSES, seed=7 if train else 8)
        else:
            images, labels = data
        feats = images.astype(np.float32) / 255.0
        onehot = np.eye(self.N_CLASSES, dtype=np.float32)[labels]
        super().__init__(feats, onehot, batch_size, shuffle=shuffle, seed=seed)

    def _load_real(self, train: bool, num_examples: Optional[int]):
        d = os.environ.get("TINY_IMAGENET_DIR")
        if not d or not (Path(d).expanduser() / "train").is_dir():
            return None
        try:
            from PIL import Image
        except ImportError:
            return None
        root = Path(d).expanduser()
        wnids = sorted(p.name for p in (root / "train").iterdir()
                       if p.is_dir())
        images, labels = [], []
        if train:
            for ci, wnid in enumerate(wnids):
                for jp in sorted((root / "train" / wnid / "images").glob("*.JPEG")):
                    images.append(np.asarray(
                        Image.open(jp).convert("RGB").resize((self.HW, self.HW))))
                    labels.append(ci)
                    if num_examples and len(images) >= num_examples:
                        break
                if num_examples and len(images) >= num_examples:
                    break
        else:
            anno = root / "val" / "val_annotations.txt"
            if not anno.exists():
                return None
            wnid_to_ci = {w: i for i, w in enumerate(wnids)}
            for line in anno.read_text().splitlines():
                parts = line.split("\t")
                if len(parts) < 2:
                    continue
                jp = root / "val" / "images" / parts[0]
                if not jp.exists():
                    continue
                images.append(np.asarray(
                    Image.open(jp).convert("RGB").resize((self.HW, self.HW))))
                labels.append(wnid_to_ci[parts[1]])
                if num_examples and len(images) >= num_examples:
                    break
        if not images:
            return None
        return np.stack(images), np.asarray(labels, np.int64)


class LFWDataSetIterator(INDArrayDataSetIterator):
    """LFW faces (reference ``LFWDataSetIterator.java`` /
    ``LFWDataFetcher``): person-labeled face images read from the standard
    extracted layout under ``LFW_DIR`` (<person_name>/<img>.jpg), synthetic
    otherwise.  Features NHWC [n, hw, hw, 3] in [0,1]; labels one-hot over
    the ``num_labels`` most-photographed people."""

    def __init__(self, batch_size: int, hw: int = 64, num_labels: int = 10,
                 num_examples: Optional[int] = None, shuffle: bool = True,
                 seed: int = 6):
        self.hw = hw
        data = self._load_real(hw, num_labels, num_examples)
        self.synthetic = data is None
        if data is None:
            n = num_examples or 1024
            images, labels = _synthetic_images(n, hw, 3, num_labels, seed=21)
        else:
            images, labels = data
        feats = images.astype(np.float32) / 255.0
        onehot = np.eye(num_labels, dtype=np.float32)[labels]
        super().__init__(feats, onehot, batch_size, shuffle=shuffle, seed=seed)

    @staticmethod
    def _load_real(hw: int, num_labels: int, num_examples: Optional[int]):
        d = os.environ.get("LFW_DIR")
        if not d or not Path(d).expanduser().is_dir():
            return None
        try:
            from PIL import Image
        except ImportError:
            return None
        root = Path(d).expanduser()
        people = [(p, sorted(p.glob("*.jpg")))
                  for p in sorted(root.iterdir()) if p.is_dir()]
        people = [(p, fs) for p, fs in people if fs]
        people.sort(key=lambda t: -len(t[1]))
        people = people[:num_labels]
        images, labels = [], []
        for ci, (_, files) in enumerate(people):
            for jp in files:
                images.append(np.asarray(
                    Image.open(jp).convert("RGB").resize((hw, hw))))
                labels.append(ci)
                if num_examples and len(images) >= num_examples:
                    break
            if num_examples and len(images) >= num_examples:
                break
        if not images:
            return None
        return np.stack(images), np.asarray(labels, np.int64)
