"""Legacy Vectorizer API (reference ``datasets/vectorizer/Vectorizer.java:33``
— "takes an input source and converts it to a matrix for neural network
consumption": a one-method contract, ``vectorize() -> DataSet``).

Superseded in practice by the RecordReader iterators (``records.py``) and the
NLP vectorizers (``nlp/vectorizer.py``); kept for API completeness, with a
text-corpus adapter bridging the modern pieces back to the legacy shape.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from .dataset import DataSet

__all__ = ["Vectorizer", "CallableVectorizer", "TextCorpusVectorizer"]


class Vectorizer:
    """``vectorize() -> DataSet`` contract (Vectorizer.java:39)."""

    def vectorize(self) -> DataSet:
        raise NotImplementedError


class CallableVectorizer(Vectorizer):
    """Adapter: any zero-arg callable returning (features, labels)."""

    def __init__(self, fn: Callable[[], tuple]):
        self._fn = fn

    def vectorize(self) -> DataSet:
        features, labels = self._fn()
        return DataSet(np.asarray(features, np.float32),
                       np.asarray(labels, np.float32))


class TextCorpusVectorizer(Vectorizer):
    """Docs + labels -> one DataSet via a fitted bag-of-words/TF-IDF
    vectorizer (the role the legacy API played before
    ``bagofwords/vectorizer`` replaced it)."""

    def __init__(self, docs: Sequence[str], labels: Sequence[int],
                 n_classes: int, tfidf: bool = True):
        if len(docs) != len(labels):
            raise ValueError(f"{len(docs)} docs but {len(labels)} labels")
        bad = [l for l in labels if not 0 <= int(l) < n_classes]
        if bad:
            raise ValueError(f"labels out of range [0, {n_classes}): {bad}")
        self.docs = list(docs)
        self.labels = list(labels)
        self.n_classes = n_classes
        self.tfidf = tfidf

    def vectorize(self) -> DataSet:
        from ..nlp.vectorizer import BagOfWordsVectorizer, TfidfVectorizer
        vec = (TfidfVectorizer() if self.tfidf else BagOfWordsVectorizer())
        feats = np.asarray(vec.fit_transform(self.docs), np.float32)
        onehot = np.eye(self.n_classes, dtype=np.float32)[
            np.asarray(self.labels, np.int64)]
        return DataSet(feats, onehot)
