"""Image transforms for training pipelines.

The DataVec ``ImageTransform`` role (the reference's CIFAR/image iterators
wrap DataVec's flip/crop/normalize pipeline — external module, SURVEY
§2.2).  Transforms are numpy, run on the prefetch thread (compose with
``AsyncDataSetIterator``), deterministic under a seeded rng, and applied
per batch via ``TransformingDataSetIterator``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator

__all__ = ["ImageTransform", "RandomFlipTransform", "RandomCropTransform",
           "CutoutTransform", "ComposeTransform",
           "TransformingDataSetIterator"]


class ImageTransform:
    """transform(features [b,h,w,c], rng) -> features."""

    def transform(self, feats: np.ndarray, rng) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, feats, rng):
        return self.transform(feats, rng)


class RandomFlipTransform(ImageTransform):
    """Horizontal (and optionally vertical) flips with probability p."""

    def __init__(self, p: float = 0.5, vertical: bool = False):
        self.p = p
        self.vertical = vertical

    def transform(self, feats, rng):
        out = feats.copy()
        flip = rng.random(len(out)) < self.p
        out[flip] = out[flip, :, ::-1]
        if self.vertical:
            flip = rng.random(len(out)) < self.p
            out[flip] = out[flip, ::-1]
        return out


class RandomCropTransform(ImageTransform):
    """Pad by ``padding`` then crop back to the original size at a random
    offset (the standard CIFAR augmentation)."""

    def __init__(self, padding: int = 4):
        self.padding = padding

    def transform(self, feats, rng):
        p = self.padding
        b, h, w = feats.shape[:3]
        pad_width = [(0, 0), (p, p), (p, p)] + \
            [(0, 0)] * (feats.ndim - 3)
        padded = np.pad(feats, pad_width, mode="reflect")
        out = np.empty_like(feats)
        ys = rng.integers(0, 2 * p + 1, b)
        xs = rng.integers(0, 2 * p + 1, b)
        for i in range(b):
            out[i] = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        return out


class CutoutTransform(ImageTransform):
    """Zero a random square patch per image (regularization)."""

    def __init__(self, size: int = 8, p: float = 0.5):
        self.size = size
        self.p = p

    def transform(self, feats, rng):
        out = feats.copy()
        b, h, w = feats.shape[:3]
        s = self.size
        for i in range(b):
            if rng.random() >= self.p:
                continue
            y = int(rng.integers(0, max(h - s, 1)))
            x = int(rng.integers(0, max(w - s, 1)))
            out[i, y:y + s, x:x + s] = 0
        return out


class ComposeTransform(ImageTransform):
    def __init__(self, transforms: Sequence[ImageTransform]):
        self.transforms = list(transforms)

    def transform(self, feats, rng):
        for t in self.transforms:
            feats = t.transform(feats, rng)
        return feats


class TransformingDataSetIterator(DataSetIterator):
    """Apply an ImageTransform to every batch's features (fresh random
    draws each epoch, seeded for reproducibility)."""

    def __init__(self, underlying: DataSetIterator,
                 transform: ImageTransform, seed: int = 0):
        self.underlying = underlying
        self.transform = transform
        self.seed = seed
        self._epoch = 0

    def batch(self):
        return self.underlying.batch()

    def reset(self):
        self._epoch += 1
        if hasattr(self.underlying, "reset"):
            self.underlying.reset()

    def __iter__(self):
        rng = np.random.default_rng((self.seed, self._epoch))
        for ds in self.underlying:
            feats = self.transform.transform(
                np.asarray(ds.features), rng)
            yield DataSet(feats, ds.labels, ds.features_mask,
                          ds.labels_mask)
