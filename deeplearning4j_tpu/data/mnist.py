"""MNIST / EMNIST-style dataset iterators.

Analogue of ``datasets/fetchers/MnistDataFetcher.java:40`` +
``datasets/iterator/impl/MnistDataSetIterator.java``: reads the standard IDX
binary format from a local cache directory (the reference downloads with
checksum; this environment has no egress, so we read ``MNIST_DIR`` /
``~/.deeplearning4j_tpu/mnist`` if present and otherwise generate a
deterministic synthetic drop-in with the same shapes/format — the
BenchmarkDataSetIterator pattern).
"""
from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .dataset import DataSet, DataSetIterator, INDArrayDataSetIterator

MNIST_NUM_EXAMPLES = 60000
MNIST_NUM_TEST = 10000


def _read_idx(path: Path) -> np.ndarray:
    """Read an IDX file (the reference's custom MnistDbFile reader,
    ``datasets/mnist/MnistDbFile.java``)."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_mnist_dir() -> Optional[Path]:
    for cand in (os.environ.get("MNIST_DIR"),
                 "~/.deeplearning4j_tpu/mnist", "~/.cache/mnist", "/data/mnist"):
        if cand is None:
            continue
        p = Path(cand).expanduser()
        if p.is_dir():
            for stem in ("train-images-idx3-ubyte", "train-images.idx3-ubyte"):
                if (p / stem).exists() or (p / (stem + ".gz")).exists():
                    return p
    return None


def _load_real(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    d = _find_mnist_dir()
    if d is None:
        return None
    img_stem = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
    lbl_stem = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"

    def find(stem):
        for s in (stem, stem.replace("-idx", ".idx")):
            for suffix in ("", ".gz"):
                p = d / (s + suffix)
                if p.exists():
                    return p
        return None

    ip, lp = find(img_stem), find(lbl_stem)
    if ip is None or lp is None:
        return None
    return _read_idx(ip), _read_idx(lp)


def _synthetic(train: bool, seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped synthetic data: 10 class-dependent blob
    patterns + noise, learnable by LeNet — serves tests and benchmarks when
    the real corpus isn't on disk."""
    n = 8192 if train else 2048
    rng = np.random.default_rng(seed if train else seed + 1)
    labels = rng.integers(0, 10, n)
    # class prototype: a bright 8x8 patch at a class-specific location
    images = (rng.standard_normal((n, 28, 28)) * 16 + 32).clip(0, 255)
    for c in range(10):
        r, col = divmod(c, 4)
        mask = labels == c
        images[mask, 4 + r * 6:12 + r * 6, 2 + col * 6:10 + col * 6] += 160
    return images.clip(0, 255).astype(np.uint8), labels.astype(np.uint8)


class MnistDataSetIterator(INDArrayDataSetIterator):
    """Reference-compatible MNIST iterator: features [batch, 784] in [0,1],
    labels one-hot [batch, 10] (``MnistDataSetIterator.java`` binarize=False
    default).  Batch slicing/shuffling is inherited from
    INDArrayDataSetIterator (partial final batch kept)."""

    def __init__(self, batch_size: int, train: bool = True,
                 num_examples: Optional[int] = None, binarize: bool = False,
                 shuffle: bool = True, seed: int = 6, flatten: bool = True):
        data = _load_real(train)
        self.synthetic = data is None
        if data is None:
            images, labels = _synthetic(train)
        else:
            images, labels = data
        if num_examples is not None:
            images, labels = images[:num_examples], labels[:num_examples]
        feats = images.astype(np.float32) / 255.0
        if binarize:
            feats = (feats > 0.5).astype(np.float32)
        features = feats.reshape(len(feats), -1) if flatten else feats[..., None]
        labels_1hot = np.eye(10, dtype=np.float32)[labels.astype(np.int64)]
        super().__init__(features, labels_1hot, batch_size,
                         shuffle=shuffle, seed=seed)

    def total_examples(self):
        return len(self.features)


class IrisDataSetIterator(INDArrayDataSetIterator):
    """Iris (reference ``datasets/iterator/impl/IrisDataSetIterator.java``).
    The 150-example Fisher iris table is small enough to embed parametrically:
    we regenerate it from the canonical per-class Gaussian stats when the CSV
    isn't on disk (IRIS_CSV env var)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150, seed: int = 12345):
        path = os.environ.get("IRIS_CSV")
        if path and Path(path).exists():
            raw = np.loadtxt(path, delimiter=",")
            feats, labels = raw[:, :4], raw[:, 4].astype(int)
        else:
            rng = np.random.default_rng(seed)
            means = np.array([[5.01, 3.43, 1.46, 0.25],
                              [5.94, 2.77, 4.26, 1.33],
                              [6.59, 2.97, 5.55, 2.03]])
            stds = np.array([[0.35, 0.38, 0.17, 0.11],
                             [0.52, 0.31, 0.47, 0.20],
                             [0.64, 0.32, 0.55, 0.27]])
            per = num_examples // 3
            feats = np.concatenate([
                means[c] + stds[c] * rng.standard_normal((per, 4))
                for c in range(3)])
            labels = np.repeat(np.arange(3), per)
        order = np.random.default_rng(seed).permutation(len(feats))
        super().__init__(feats[order].astype(np.float32),
                         np.eye(3, dtype=np.float32)[labels[order]],
                         batch_size)
