"""Dataset normalizers.

Analogue of the nd4j DataNormalization stack the reference trains with
(``NormalizerStandardize``, ``NormalizerMinMaxScaler``,
``ImagePreProcessingScaler`` — external nd4j classes, referenced all over
the examples and Spark masters): fit statistics over an iterator, then
transform (and optionally revert) batches; serializable so serving sees
the exact training-time preprocessing.
"""
from __future__ import annotations

import json
from typing import Optional

import numpy as np

from .dataset import DataSet

__all__ = ["NormalizerStandardize", "NormalizerMinMaxScaler",
           "ImagePreProcessingScaler", "load_normalizer"]


class _BaseNormalizer:
    KIND = "base"
    _EPS = 1e-8

    def __init__(self):
        self.fit_labels = False

    def fit_label(self, fit_labels: bool = True) -> "_BaseNormalizer":
        """Also normalize labels (regression targets) — reference
        ``fitLabel``."""
        self.fit_labels = fit_labels
        return self

    # -- iterator plumbing ---------------------------------------------------
    def _batches(self, data):
        if isinstance(data, DataSet):
            yield data
            return
        if hasattr(data, "reset"):
            data.reset()
        for b in data:
            yield b if isinstance(b, DataSet) else DataSet(b[0], b[1])

    def fit(self, data) -> "_BaseNormalizer":
        """Streaming fit: per-batch running accumulators, O(features)
        memory — the dataset is never materialized (nd4j normalizers use
        the same running-stats approach)."""
        self._begin_fit()
        for ds in self._batches(data):
            # host ETL, not a device fetch: batches come from the host
            # iterator and the running accumulators are numpy
            self._update_fit(np.asarray(ds.features, np.float64),  # graftlint: disable=JX003
                             np.asarray(ds.labels, np.float64)  # graftlint: disable=JX003
                             if self.fit_labels else None)
        self._finish_fit()
        return self

    def _begin_fit(self):
        raise NotImplementedError

    def _update_fit(self, feats, labels):
        raise NotImplementedError

    def _finish_fit(self):
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        f = self._tx(np.asarray(ds.features, np.float32), False)
        l = ds.labels
        if self.fit_labels:
            l = self._tx(np.asarray(ds.labels, np.float32), True)
        return DataSet(f, l, ds.features_mask, ds.labels_mask)

    def revert(self, ds: DataSet) -> DataSet:
        f = self._untx(np.asarray(ds.features, np.float32), False)
        l = ds.labels
        if self.fit_labels:
            l = self._untx(np.asarray(ds.labels, np.float32), True)
        return DataSet(f, l, ds.features_mask, ds.labels_mask)

    def pre_process(self, ds: DataSet) -> DataSet:  # reference naming
        return self.transform(ds)

    def wrap(self, iterator):
        """Iterator adapter applying this normalizer per batch (the
        reference attaches normalizers via setPreProcessor)."""
        norm = self

        class _It:
            def batch(self):
                return iterator.batch()

            def reset(self):
                if hasattr(iterator, "reset"):
                    iterator.reset()

            def __iter__(self):
                if hasattr(iterator, "reset"):
                    iterator.reset()
                for ds in iterator:
                    yield norm.transform(
                        ds if isinstance(ds, DataSet)
                        else DataSet(ds[0], ds[1]))

        return _It()

    # -- persistence ---------------------------------------------------------
    def save(self, path) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"kind": self.KIND, "fit_labels": self.fit_labels,
                       "stats": self._stats_dict()}, fh)

    def _stats_dict(self):
        raise NotImplementedError

    def _load_stats(self, d):
        raise NotImplementedError


class NormalizerStandardize(_BaseNormalizer):
    """Zero-mean unit-variance per feature column (reference
    NormalizerStandardize)."""
    KIND = "standardize"

    def __init__(self):
        super().__init__()
        self.mean = self.std = None
        self.label_mean = self.label_std = None

    def _begin_fit(self):
        self._acc = {}

    @staticmethod
    def _acc_update(acc, key, a):
        flat = a.reshape(-1, a.shape[-1])
        n, sm, sq = acc.get(key, (0, 0.0, 0.0))
        acc[key] = (n + flat.shape[0], sm + flat.sum(0),
                    sq + (flat * flat).sum(0))

    @staticmethod
    def _acc_final(acc, key):
        n, sm, sq = acc[key]
        mean = sm / max(n, 1)
        var = np.maximum(sq / max(n, 1) - mean * mean, 0.0)
        return mean, np.sqrt(var)

    def _update_fit(self, feats, labels):
        self._acc_update(self._acc, "f", feats)
        if labels is not None:
            self._acc_update(self._acc, "l", labels)

    def _finish_fit(self):
        self.mean, self.std = self._acc_final(self._acc, "f")
        if "l" in self._acc:
            self.label_mean, self.label_std = self._acc_final(self._acc, "l")
        del self._acc

    def _tx(self, a, is_label):
        m, s = ((self.label_mean, self.label_std) if is_label
                else (self.mean, self.std))
        return ((a - m) / np.maximum(s, self._EPS)).astype(np.float32)

    def _untx(self, a, is_label):
        m, s = ((self.label_mean, self.label_std) if is_label
                else (self.mean, self.std))
        return (a * np.maximum(s, self._EPS) + m).astype(np.float32)

    def _stats_dict(self):
        out = {"mean": self.mean.tolist(), "std": self.std.tolist()}
        if self.label_mean is not None:
            out["label_mean"] = self.label_mean.tolist()
            out["label_std"] = self.label_std.tolist()
        return out

    def _load_stats(self, d):
        self.mean = np.asarray(d["mean"])
        self.std = np.asarray(d["std"])
        if "label_mean" in d:
            self.label_mean = np.asarray(d["label_mean"])
            self.label_std = np.asarray(d["label_std"])


class NormalizerMinMaxScaler(_BaseNormalizer):
    """Scale per feature column into [lo, hi] (reference
    NormalizerMinMaxScaler)."""
    KIND = "minmax"

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        super().__init__()
        self.lo, self.hi = float(lo), float(hi)
        self.min = self.max = None
        self.label_min = self.label_max = None

    def _begin_fit(self):
        self.min = self.max = None
        self.label_min = self.label_max = None

    def _update_fit(self, feats, labels):
        flat = feats.reshape(-1, feats.shape[-1])
        lo, hi = flat.min(0), flat.max(0)
        self.min = lo if self.min is None else np.minimum(self.min, lo)
        self.max = hi if self.max is None else np.maximum(self.max, hi)
        if labels is not None:
            lf = labels.reshape(-1, labels.shape[-1])
            llo, lhi = lf.min(0), lf.max(0)
            self.label_min = llo if self.label_min is None else                 np.minimum(self.label_min, llo)
            self.label_max = lhi if self.label_max is None else                 np.maximum(self.label_max, lhi)

    def _finish_fit(self):
        pass

    def _scale(self, a, lo_v, hi_v):
        rng = np.maximum(hi_v - lo_v, self._EPS)
        return ((a - lo_v) / rng * (self.hi - self.lo) + self.lo).astype(
            np.float32)

    def _tx(self, a, is_label):
        lo_v, hi_v = ((self.label_min, self.label_max) if is_label
                      else (self.min, self.max))
        return self._scale(a, lo_v, hi_v)

    def _untx(self, a, is_label):
        lo_v, hi_v = ((self.label_min, self.label_max) if is_label
                      else (self.min, self.max))
        rng = np.maximum(hi_v - lo_v, self._EPS)
        return (((a - self.lo) / max(self.hi - self.lo, self._EPS)) * rng
                + lo_v).astype(np.float32)

    def _stats_dict(self):
        out = {"lo": self.lo, "hi": self.hi, "min": self.min.tolist(),
               "max": self.max.tolist()}
        if self.label_min is not None:
            out["label_min"] = self.label_min.tolist()
            out["label_max"] = self.label_max.tolist()
        return out

    def _load_stats(self, d):
        self.lo, self.hi = d["lo"], d["hi"]
        self.min = np.asarray(d["min"])
        self.max = np.asarray(d["max"])
        if "label_min" in d:
            self.label_min = np.asarray(d["label_min"])
            self.label_max = np.asarray(d["label_max"])


class ImagePreProcessingScaler(_BaseNormalizer):
    """Fixed-range pixel scaling, no fitting needed: [0, max_pixel] →
    [lo, hi] (reference ImagePreProcessingScaler)."""
    KIND = "image"

    def __init__(self, lo: float = 0.0, hi: float = 1.0,
                 max_pixel: float = 255.0):
        super().__init__()
        self.lo, self.hi = float(lo), float(hi)
        self.max_pixel = float(max_pixel)

    def fit(self, data):  # stateless
        return self

    def _tx(self, a, is_label):
        if is_label:
            return a
        return (a / self.max_pixel * (self.hi - self.lo) + self.lo).astype(
            np.float32)

    def _untx(self, a, is_label):
        if is_label:
            return a
        return ((a - self.lo) / max(self.hi - self.lo, self._EPS)
                * self.max_pixel).astype(np.float32)

    def _stats_dict(self):
        return {"lo": self.lo, "hi": self.hi, "max_pixel": self.max_pixel}

    def _load_stats(self, d):
        self.lo, self.hi = d["lo"], d["hi"]
        self.max_pixel = d["max_pixel"]


_KINDS = {c.KIND: c for c in (NormalizerStandardize, NormalizerMinMaxScaler,
                              ImagePreProcessingScaler)}


def load_normalizer(path):
    """Restore any saved normalizer (reference NormalizerSerializer)."""
    with open(path, encoding="utf-8") as fh:
        d = json.load(fh)
    norm = _KINDS[d["kind"]]()
    norm.fit_labels = d.get("fit_labels", False)
    norm._load_stats(d["stats"])
    return norm
