"""DataSet + iterator framework.

Analogue of nd4j ``DataSet`` and the reference iterator stack
(``deeplearning4j-nn/.../datasets/iterator/`` — 26 classes, and
``deeplearning4j-core/.../datasets/iterator/impl/``): base ``DataSetIterator``
protocol, array-backed and synthetic/benchmark iterators, wrappers
(EarlyTermination, MultipleEpochs, Sampling, Async prefetch).

Iterators yield host-side numpy batches; device transfer happens once per
batch inside the jitted step (single host→HBM hop — the reference's
AsyncDataSetIterator device-affinity prefetch maps to our AsyncDataSetIterator
background thread + jax device_put pipelining).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np


class DataSet:
    """features/labels (+ masks) container (nd4j DataSet role)."""

    def __init__(self, features, labels, features_mask=None, labels_mask=None):
        self.features = features
        self.labels = labels
        self.features_mask = features_mask
        self.labels_mask = labels_mask

    def num_examples(self) -> int:
        return self.features.shape[0]

    def split_test_and_train(self, n_train: int):
        a = DataSet(self.features[:n_train], self.labels[:n_train],
                    None if self.features_mask is None else self.features_mask[:n_train],
                    None if self.labels_mask is None else self.labels_mask[:n_train])
        b = DataSet(self.features[n_train:], self.labels[n_train:],
                    None if self.features_mask is None else self.features_mask[n_train:],
                    None if self.labels_mask is None else self.labels_mask[n_train:])
        return a, b

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def __iter__(self):
        yield self.features
        yield self.labels
        yield self.features_mask
        yield self.labels_mask


class DataSetIterator:
    """Iterator protocol (reference DataSetIterator): iterable of DataSet with
    reset()."""

    def reset(self) -> None:
        pass

    def batch(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError


class INDArrayDataSetIterator(DataSetIterator):
    """Batched iteration over in-memory arrays (reference
    INDArrayDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int,
                 features_mask=None, labels_mask=None, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = features_mask
        self.labels_mask = labels_mask
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0

    def batch(self):
        return self.batch_size

    def reset(self):
        self._epoch += 1

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            np.random.default_rng(self.seed + self._epoch).shuffle(idx)
        stop = n - (n % self.batch_size) if self.drop_last else n
        for i in range(0, stop, self.batch_size):
            sl = idx[i:i + self.batch_size]
            yield DataSet(
                self.features[sl], self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl])


class ExistingDataSetIterator(DataSetIterator):
    """Wrap a list of DataSets (reference ExistingDataSetIterator)."""

    def __init__(self, datasets: List[DataSet]):
        self.datasets = list(datasets)

    def batch(self):
        return self.datasets[0].num_examples() if self.datasets else 0

    def __iter__(self):
        return iter(self.datasets)


class BenchmarkDataSetIterator(DataSetIterator):
    """Fixed synthetic batch repeated N times (reference
    ``datasets/iterator/impl/BenchmarkDataSetIterator.java``) — zero ETL cost,
    used to measure pure compute throughput."""

    def __init__(self, feature_shape, n_classes: int, n_batches: int,
                 seed: int = 42, label_shape=None):
        rng = np.random.default_rng(seed)
        self.features = rng.standard_normal(feature_shape).astype(np.float32)
        batch = feature_shape[0]
        if label_shape is not None:
            self.labels = rng.standard_normal(label_shape).astype(np.float32)
        else:
            cls = rng.integers(0, n_classes, batch)
            self.labels = np.zeros((batch, n_classes), np.float32)
            self.labels[np.arange(batch), cls] = 1.0
        self.n_batches = n_batches

    def batch(self):
        return self.features.shape[0]

    def __iter__(self):
        for _ in range(self.n_batches):
            yield DataSet(self.features, self.labels)


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of batches (reference EarlyTerminationDataSetIterator)."""

    def __init__(self, underlying: DataSetIterator, max_batches: int):
        self.underlying = underlying
        self.max_batches = max_batches

    def batch(self):
        return self.underlying.batch()

    def reset(self):
        self.underlying.reset()

    def __iter__(self):
        for i, ds in enumerate(self.underlying):
            if i >= self.max_batches:
                break
            yield ds


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an iterator N epochs (reference MultipleEpochsIterator)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self.epochs = epochs
        self.underlying = underlying

    def batch(self):
        return self.underlying.batch()

    def reset(self):
        self.underlying.reset()

    def __iter__(self):
        for e in range(self.epochs):
            if e > 0:
                self.underlying.reset()
            yield from self.underlying


class SamplingDataSetIterator(DataSetIterator):
    """Sample random batches with replacement (reference
    SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int, total_batches: int,
                 seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.seed = seed
        self._epoch = 0

    def batch(self):
        return self.batch_size

    def reset(self):
        self._epoch += 1

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self._epoch)
        n = self.dataset.num_examples()
        for _ in range(self.total_batches):
            sl = rng.integers(0, n, self.batch_size)
            yield DataSet(self.dataset.features[sl], self.dataset.labels[sl])


class MovingWindowDataSetIterator(DataSetIterator):
    """Slide a (rows, cols) window over each image example, emitting one
    sub-image example per window position with the source label (reference
    ``MovingWindowBaseDataSetIterator`` + ``util/MovingWindowMatrix.java``).
    Features [n, h, w] or [n, h, w, c]; stride defaults to the window size
    (non-overlapping, the reference's behavior)."""

    def __init__(self, dataset: DataSet, batch_size: int, window_rows: int,
                 window_cols: int, stride_rows: Optional[int] = None,
                 stride_cols: Optional[int] = None):
        feats = np.asarray(dataset.features)
        if feats.ndim not in (3, 4):
            raise ValueError(
                f"MovingWindow needs image features [n,h,w(,c)], got "
                f"shape {feats.shape}")
        labels = np.asarray(dataset.labels)
        sr = stride_rows or window_rows
        sc = stride_cols or window_cols
        h, w = feats.shape[1], feats.shape[2]
        if window_rows > h or window_cols > w:
            raise ValueError(f"window ({window_rows},{window_cols}) exceeds "
                             f"image ({h},{w})")
        wins, labs = [], []
        for r0 in range(0, h - window_rows + 1, sr):
            for c0 in range(0, w - window_cols + 1, sc):
                wins.append(feats[:, r0:r0 + window_rows,
                                  c0:c0 + window_cols])
                labs.append(labels)
        self._inner = INDArrayDataSetIterator(
            np.concatenate(wins), np.concatenate(labs), batch_size,
            shuffle=False)

    def batch(self):
        return self._inner.batch()

    def reset(self):
        self._inner.reset()

    def __iter__(self):
        return iter(self._inner)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference
    ``datasets/iterator/AsyncDataSetIterator.java:30`` + MagicQueue).  The
    producer thread fills a bounded queue so host-side ETL overlaps device
    compute — the TPU equivalent of the reference's device-affinity prefetch
    threads.

    Not re-entrant: one live iteration at a time.  Two concurrent
    iterations would race two producer threads over the ONE underlying
    iterator (interleaving/dropping batches nondeterministically), so a
    second ``__iter__`` while the first is still running raises instead."""

    _SENTINEL = object()

    def __init__(self, underlying: DataSetIterator, queue_size: int = 4):
        # AsyncShieldDataSetIterator is defined below in this module; it
        # exists by the time any caller constructs an async wrapper
        if isinstance(underlying, AsyncShieldDataSetIterator):
            raise ValueError(
                "iterator is wrapped in AsyncShieldDataSetIterator — it must "
                "not be prefetched from a background thread")
        self.underlying = underlying
        self.queue_size = queue_size
        self._state_lock = threading.Lock()
        self._active = False

    def batch(self):
        return self.underlying.batch()

    def reset(self):
        self.underlying.reset()

    def __iter__(self):
        with self._state_lock:
            if self._active:
                raise RuntimeError(
                    "AsyncDataSetIterator is already being iterated — a "
                    "concurrent second iteration would race two producer "
                    "threads over one underlying iterator; finish (or "
                    "close) the first iteration, or give each consumer its "
                    "own wrapper")
            self._active = True
        try:
            yield from self._iterate()
        finally:
            with self._state_lock:
                self._active = False

    def _iterate(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        err: List[BaseException] = []

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for ds in self.underlying:
                    if not _put(ds):
                        return  # consumer went away
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                err.append(e)
            finally:
                _put(self._SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    break
                yield item
        finally:
            # consumer stopped early (break/exception/GC): release the producer
            stop.set()
            t.join()
        if err:
            raise err[0]


# --------------------------------------------------------------------------
# file-backed DataSets (reference: spark export-then-fitPaths flow,
# datasets/iterator/parallel/ file-split iterators + callbacks/)
# --------------------------------------------------------------------------

def _dataset_to_bytes(ds: DataSet) -> bytes:
    from ..streaming.codec import serialize_dataset
    return serialize_dataset(np.asarray(ds.features), np.asarray(ds.labels),
                             None if ds.features_mask is None
                             else np.asarray(ds.features_mask),
                             None if ds.labels_mask is None
                             else np.asarray(ds.labels_mask))


def _dataset_from_bytes(data: bytes) -> DataSet:
    from ..streaming.codec import deserialize_dataset
    f, l, fm, lm = deserialize_dataset(data)
    return DataSet(f, l, fm, lm)


def save_dataset(ds: DataSet, path) -> None:
    """One DataSet -> one binary file (reference DataSet.save)."""
    with open(path, "wb") as fh:
        fh.write(_dataset_to_bytes(ds))


def load_dataset(path) -> DataSet:
    with open(path, "rb") as fh:
        return _dataset_from_bytes(fh.read())


def export_dataset_batches(iterator, directory, prefix: str = "dataset"
                           ) -> List[str]:
    """Write every batch of an iterator to ``directory`` (the Spark
    export-to-disk step before ``fitPaths``,
    ``spark/data/DataSetExportFunction`` role).  Returns the paths."""
    import os
    os.makedirs(directory, exist_ok=True)
    paths = []
    if hasattr(iterator, "reset"):
        iterator.reset()
    for i, b in enumerate(iterator):
        ds = b if isinstance(b, DataSet) else DataSet(*b) if isinstance(
            b, (tuple, list)) else b
        p = os.path.join(directory, f"{prefix}_{i:06d}.bin")
        save_dataset(ds, p)
        paths.append(p)
    return paths


class DataSetCallback:
    """Hook applied to each loaded DataSet before it reaches the trainer
    (reference ``datasets/iterator/callbacks/DataSetCallback.java`` — e.g.
    device placement or augmentation on the prefetch thread)."""

    def call(self, ds: DataSet) -> DataSet:
        return ds


class FileSplitDataSetIterator(DataSetIterator):
    """Iterate serialized DataSet files; ``worker``/``num_workers`` select
    an interleaved shard of the file list (reference
    ``datasets/iterator/parallel/FileSplitParallelDataSetIterator.java``
    + ``InterleavedDataSetCallback`` role via ``callback``)."""

    def __init__(self, paths_or_dir, callback: Optional[DataSetCallback] = None,
                 worker: int = 0, num_workers: int = 1):
        import os
        if isinstance(paths_or_dir, (str, bytes)) or hasattr(
                paths_or_dir, "is_dir"):
            d = str(paths_or_dir)
            if os.path.isdir(d):
                paths = sorted(os.path.join(d, f) for f in os.listdir(d)
                               if f.endswith(".bin"))
            else:
                paths = [d]
        else:
            paths = [str(p) for p in paths_or_dir]
        if not 0 <= worker < num_workers:
            raise ValueError(f"worker {worker} outside 0..{num_workers - 1}")
        self.paths = paths[worker::num_workers]
        self.callback = callback

    def batch(self):
        return -1

    def reset(self):
        pass

    def __iter__(self) -> Iterator[DataSet]:
        for p in self.paths:
            ds = load_dataset(p)
            if self.callback is not None:
                ds = self.callback.call(ds)
            yield ds


class MultiDataSet:
    """Multi-input/multi-output container (nd4j MultiDataSet role):
    features/labels are LISTS of arrays — the ComputationGraph batch
    shape."""

    def __init__(self, features, labels, features_masks=None,
                 labels_masks=None):
        as_list = lambda v: list(v) if isinstance(v, (list, tuple)) else [v]
        self.features = as_list(features)
        self.labels = as_list(labels)
        self.features_mask = (None if features_masks is None
                              else as_list(features_masks))
        self.labels_mask = (None if labels_masks is None
                            else as_list(labels_masks))

    def num_examples(self) -> int:
        return self.features[0].shape[0]

    def __iter__(self):
        yield self.features
        yield self.labels
        yield self.features_mask
        yield self.labels_mask


# The prefetch loop is payload-agnostic (it queues whatever the underlying
# iterator yields), so the MultiDataSet variant (reference
# ``AsyncMultiDataSetIterator``) is the same class.
AsyncMultiDataSetIterator = AsyncDataSetIterator


class DataSetPreProcessor:
    """``pre_process(DataSet) -> None`` contract (nd4j DataSetPreProcessor;
    mutates the batch in place before the model sees it)."""

    def pre_process(self, ds: DataSet) -> None:
        raise NotImplementedError


class DummyPreProcessor(DataSetPreProcessor):
    """No-op (reference ``DummyPreProcessor.java``)."""

    def pre_process(self, ds: DataSet) -> None:
        pass


class CombinedPreProcessor(DataSetPreProcessor):
    """Apply several preprocessors in order (reference
    ``CombinedPreProcessor.java`` builder; also serves the
    CombinedMultiDataSetPreProcessor role — members just need
    ``pre_process``)."""

    def __init__(self, *pre_processors: DataSetPreProcessor):
        self.pre_processors = list(pre_processors)

    def add_pre_processor(self, pp: DataSetPreProcessor) -> "CombinedPreProcessor":
        self.pre_processors.append(pp)
        return self

    def pre_process(self, ds: DataSet) -> None:
        for pp in self.pre_processors:
            pp.pre_process(ds)


class PreProcessedDataSetIterator(DataSetIterator):
    """Wrap an iterator, applying a DataSetPreProcessor to every batch (the
    reference attaches this via ``DataSetIterator.setPreProcessor``)."""

    def __init__(self, iterator: DataSetIterator,
                 pre_processor: DataSetPreProcessor):
        self.iterator = iterator
        self.pre_processor = pre_processor

    def reset(self):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()

    def batch(self):
        return self.iterator.batch()

    def __iter__(self):
        for ds in self.iterator:
            self.pre_processor.pre_process(ds)
            yield ds


class AsyncShieldDataSetIterator(DataSetIterator):
    """Marker wrapper that prevents async prefetching of the underlying
    iterator (reference ``AsyncShieldDataSetIterator.java``: used when
    batches must be produced on the training thread, e.g. the source is not
    thread-safe).  ``AsyncDataSetIterator`` refuses to wrap it."""

    def __init__(self, iterator: DataSetIterator):
        self.iterator = iterator

    def reset(self):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()

    def batch(self):
        return self.iterator.batch()

    def __iter__(self):
        return iter(self.iterator)


#: reference has a separate AsyncShieldMultiDataSetIterator; MultiDataSet
#: batches flow through the same wrapper here
AsyncShieldMultiDataSetIterator = AsyncShieldDataSetIterator


class _PairsDataSetIterator(DataSetIterator):
    """Batched iteration over an iterable of (features, labels) pairs."""

    _dtype = np.float32

    def __init__(self, pairs, batch_size: int):
        self._pairs = list(pairs)
        self.batch_size = batch_size

    def batch(self):
        return self.batch_size

    def __iter__(self):
        for i in range(0, len(self._pairs), self.batch_size):
            chunk = self._pairs[i:i + self.batch_size]
            f = np.stack([np.asarray(p[0], dtype=self._dtype) for p in chunk])
            l = np.stack([np.asarray(p[1], dtype=self._dtype) for p in chunk])
            yield DataSet(f, l)


class FloatsDataSetIterator(_PairsDataSetIterator):
    """(float32) reference ``FloatsDataSetIterator.java``."""
    _dtype = np.float32


class DoublesDataSetIterator(_PairsDataSetIterator):
    """(float64) reference ``DoublesDataSetIterator.java``."""
    _dtype = np.float64


class IteratorDataSetIterator(DataSetIterator):
    """Re-batch an iterator of DataSets to a target minibatch size
    (reference ``IteratorDataSetIterator.java``: splits/joins incoming
    examples so every yielded batch has ``batch_size`` rows; also serves the
    IteratorMultiDataSetIterator role for single-input sets)."""

    def __init__(self, iterator, batch_size: int):
        self._source = iterator
        self.batch_size = batch_size

    def batch(self):
        return self.batch_size

    def reset(self):
        if hasattr(self._source, "reset"):
            self._source.reset()

    def __iter__(self):
        # four parallel buffers: features, labels, and the optional masks
        # (masks must survive re-batching — dropping them would silently
        # un-mask padded RNN timesteps).  Mask presence must be consistent
        # across the stream: flipping mid-stream would emit some re-batched
        # sets with masks and some without, so mixing raises instead.
        bufs = [[], [], [], []]
        have = 0
        has_mask = [None, None]   # None = undecided yet

        def _emit(lo, hi):
            cat = [np.concatenate(b)[lo:hi] if b else None for b in bufs]
            return DataSet(cat[0], cat[1],
                           cat[2] if has_mask[0] else None,
                           cat[3] if has_mask[1] else None)

        def _trim(b, keep):
            return [np.concatenate(b)[keep:]] if b else []

        for ds in self._source:
            parts = [np.asarray(ds.features), np.asarray(ds.labels),
                     ds.features_mask, ds.labels_mask]
            for j in range(2):
                present = parts[2 + j] is not None
                if has_mask[j] is None:
                    has_mask[j] = present
                elif has_mask[j] != present:
                    which = "features" if j == 0 else "labels"
                    raise ValueError(
                        f"IteratorDataSetIterator: inconsistent {which}_mask "
                        "presence across incoming batches (some batches "
                        "carry a mask, others do not)")
                if present:
                    bufs[2 + j].append(np.asarray(parts[2 + j]))
            bufs[0].append(parts[0])
            bufs[1].append(parts[1])
            have += parts[0].shape[0]
            while have >= self.batch_size:
                yield _emit(0, self.batch_size)
                bufs = [_trim(b, self.batch_size) for b in bufs]
                have = bufs[0][0].shape[0] if bufs[0] else 0
        if have:
            yield _emit(0, None)


class MultiDataSetWrapperIterator(DataSetIterator):
    """Adapt a single-input/single-output MultiDataSet iterator to the
    DataSet protocol (reference ``MultiDataSetWrapperIterator.java``)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def reset(self):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()

    def batch(self):
        return self.iterator.batch()

    @staticmethod
    def _single(value, kind: str, required: bool = False):
        if isinstance(value, (list, tuple)):
            if len(value) != 1:
                if not value and not required:
                    return None
                raise ValueError(
                    f"MultiDataSetWrapperIterator needs exactly one {kind} "
                    f"array, got {len(value)}")
            return value[0]
        return value

    def __iter__(self):
        for mds in self.iterator:
            yield DataSet(
                self._single(mds.features, "input", required=True),
                self._single(mds.labels, "output", required=True),
                self._single(getattr(mds, "features_mask", None),
                             "input mask"),
                self._single(getattr(mds, "labels_mask", None),
                             "label mask"))


class ReconstructionDataSetIterator(DataSetIterator):
    """labels := features (autoencoder targets; reference
    ``ReconstructionDataSetIterator.java``)."""

    def __init__(self, iterator: DataSetIterator):
        self.iterator = iterator

    def reset(self):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()

    def batch(self):
        return self.iterator.batch()

    def __iter__(self):
        for ds in self.iterator:
            yield DataSet(ds.features, ds.features,
                          ds.features_mask, ds.features_mask)


class JointParallelDataSetIterator(DataSetIterator):
    """Interleave several source iterators round-robin (reference
    ``parallel/JointParallelDataSetIterator.java`` with ``InequalityHandling``
    for sources of different length: ``stop`` ends the epoch when any source
    is exhausted, ``pass`` skips exhausted sources, ``reset`` restarts an
    exhausted source — the reference's STOP_EVERYONE / PASS_NULL /
    RESET per-source policy enums)."""

    def __init__(self, *iterators, inequality: str = "pass"):
        if inequality not in ("stop", "pass", "reset"):
            raise ValueError(f"unknown inequality handling '{inequality}'; "
                             "expected stop|pass|reset")
        self.iterators = list(iterators)
        self.inequality = inequality

    def reset(self):
        for it in self.iterators:
            if hasattr(it, "reset"):
                it.reset()

    def batch(self):
        return self.iterators[0].batch()

    def __iter__(self):
        actives = [iter(it) for it in self.iterators]
        exhausted = [False] * len(actives)   # stop yielding from this source
        drained = [False] * len(actives)     # has run dry at least once
        while True:
            progressed = False
            for i, src in enumerate(actives):
                if exhausted[i]:
                    continue
                try:
                    yield next(src)
                    progressed = True
                except StopIteration:
                    drained[i] = True
                    if self.inequality == "stop":
                        return
                    if self.inequality == "reset":
                        # epoch ends once EVERY source has run dry once
                        # (reference RESET policy) — until then, restart
                        if all(drained):
                            return
                        if hasattr(self.iterators[i], "reset"):
                            self.iterators[i].reset()
                        actives[i] = iter(self.iterators[i])
                        try:
                            yield next(actives[i])
                            progressed = True
                            continue
                        except StopIteration:
                            pass
                    exhausted[i] = True
            if all(exhausted) or not progressed:
                return


class FileSplitParallelDataSetIterator(JointParallelDataSetIterator):
    """Joint-parallel iteration over saved dataset files matching a pattern
    (reference ``parallel/FileSplitParallelDataSetIterator.java``: one
    FileSplitDataSetIterator per shard, interleaved)."""

    def __init__(self, directory, n_shards: int = 2,
                 inequality: str = "pass"):
        # FileSplitDataSetIterator already owns the interleaved sharding
        # (worker/num_workers); this class just joins the shards
        shards = [FileSplitDataSetIterator(directory, worker=i,
                                           num_workers=n_shards)
                  for i in range(n_shards)]
        shards = [s for s in shards if s.paths]
        if not shards:
            raise FileNotFoundError(f"no .bin dataset files in {directory}")
        super().__init__(*shards, inequality=inequality)
