"""Record readers + adapters (the DataVec bridge role: reference
``datasets/datavec/RecordReaderDataSetIterator.java``,
``SequenceRecordReaderDataSetIterator.java`` over DataVec's CSV readers).

Record readers yield lists of float records; the iterators assemble them
into DataSets (classification one-hot, regression passthrough, or sequence
tensors with masking for ragged lengths).
"""
from __future__ import annotations

import csv
import os
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator

__all__ = ["CSVRecordReader", "CSVSequenceRecordReader",
           "CollectionRecordReader", "RecordReaderDataSetIterator",
           "SequenceRecordReaderDataSetIterator"]


class RecordReader:
    """Iterable of per-example records (list of floats)."""

    def __iter__(self) -> Iterator[List[float]]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CSVRecordReader(RecordReader):
    """One record per CSV line (reference DataVec ``CSVRecordReader``)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        with open(self.path, newline="") as fh:
            reader = csv.reader(fh, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [float(v) for v in row]


class CollectionRecordReader(RecordReader):
    """In-memory records (reference ``CollectionRecordReader``) — test tier."""

    def __init__(self, records: Sequence[Sequence[float]]):
        self.records = [list(map(float, r)) for r in records]

    def __iter__(self):
        return iter([list(r) for r in self.records])


class CSVSequenceRecordReader(RecordReader):
    """One sequence per FILE in a directory (reference DataVec
    ``CSVSequenceRecordReader``); yields [T, n_cols] float arrays."""

    def __init__(self, directory: str, skip_lines: int = 0,
                 delimiter: str = ",", glob: str = "*.csv"):
        self.directory = directory
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.glob = glob

    def __iter__(self):
        for f in sorted(Path(self.directory).glob(self.glob)):
            rows = []
            with open(f, newline="") as fh:
                for i, row in enumerate(csv.reader(fh, delimiter=self.delimiter)):
                    if i < self.skip_lines or not row:
                        continue
                    rows.append([float(v) for v in row])
            yield np.asarray(rows, dtype=np.float32)


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSets (reference
    ``RecordReaderDataSetIterator.java``): ``label_index`` column becomes the
    one-hot label (classification, ``n_classes`` set) or the regression
    target range (``regression=True``, ``label_index_to`` inclusive)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, n_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.n_classes = n_classes
        self.regression = regression
        self.label_index_to = label_index_to
        if not regression and n_classes is None:
            raise ValueError("classification needs n_classes "
                             "(or pass regression=True)")

    def batch(self) -> int:
        return self.batch_size

    def reset(self):
        self.reader.reset()

    def _split(self, rec: List[float]):
        li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
        if self.regression:
            hi = (self.label_index_to if self.label_index_to is not None
                  else li)
            hi = hi if hi >= 0 else len(rec) + hi
            label = rec[li:hi + 1]
            feats = rec[:li] + rec[hi + 1:]
        else:
            label = [rec[li]]
            feats = rec[:li] + rec[li + 1:]
        return feats, label

    def __iter__(self):
        feats, labels = [], []
        for rec in self.reader:
            f, l = self._split(rec)
            feats.append(f)
            labels.append(l)
            if len(feats) == self.batch_size:
                yield self._make(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._make(feats, labels)

    def _make(self, feats, labels):
        x = np.asarray(feats, dtype=np.float32)
        if self.regression:
            y = np.asarray(labels, dtype=np.float32)
        else:
            idx = np.asarray(labels, dtype=np.int64).reshape(-1)
            y = np.eye(self.n_classes, dtype=np.float32)[idx]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Per-file sequences -> padded+masked RNN DataSets (reference
    ``SequenceRecordReaderDataSetIterator`` ALIGN_END=False semantics:
    sequences padded at the END, mask marks valid steps)."""

    def __init__(self, features_reader: CSVSequenceRecordReader,
                 labels_reader: Optional[CSVSequenceRecordReader],
                 batch_size: int, n_classes: Optional[int] = None,
                 regression: bool = False, label_index: int = -1):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.n_classes = n_classes
        self.regression = regression
        self.label_index = label_index
        if not regression and n_classes is None:
            raise ValueError("classification needs n_classes "
                             "(or pass regression=True)")

    def batch(self) -> int:
        return self.batch_size

    def _pairs(self):
        if self.labels_reader is not None:
            yield from zip(iter(self.features_reader),
                           iter(self.labels_reader))
        else:  # label column inside the same sequence file
            for seq in self.features_reader:
                li = (self.label_index if self.label_index >= 0
                      else seq.shape[1] + self.label_index)
                lab = seq[:, li:li + 1]
                feat = np.delete(seq, li, axis=1)
                yield feat, lab

    def __iter__(self):
        buf = []
        for pair in self._pairs():
            buf.append(pair)
            if len(buf) == self.batch_size:
                yield self._make(buf)
                buf = []
        if buf:
            yield self._make(buf)

    def _make(self, pairs):
        t_max = max(f.shape[0] for f, _ in pairs)
        n = len(pairs)
        nf = pairs[0][0].shape[1]
        x = np.zeros((n, t_max, nf), np.float32)
        mask = np.zeros((n, t_max), np.float32)
        if self.regression:
            nl = pairs[0][1].shape[1]
            y = np.zeros((n, t_max, nl), np.float32)
        else:
            y = np.zeros((n, t_max, self.n_classes), np.float32)
        for i, (f, l) in enumerate(pairs):
            t = f.shape[0]
            x[i, :t] = f
            mask[i, :t] = 1.0
            if self.regression:
                y[i, :t] = l
            else:
                idx = np.asarray(l, dtype=np.int64).reshape(-1)
                y[i, :t] = np.eye(self.n_classes, dtype=np.float32)[idx]
        return DataSet(x, y, features_mask=mask, labels_mask=mask)


class RecordReaderMultiDataSetIterator:
    """Multiple record readers -> MultiDataSet batches (reference
    ``RecordReaderMultiDataSetIterator.java`` builder: named readers with
    per-reader input/output column selections).

    Usage::

        it = (RecordReaderMultiDataSetIterator.builder(batch_size=32)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)            # columns 0..3 inclusive
              .add_output_one_hot("csv", 4, 3)   # column 4, 3 classes
              .build())
    """

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self.readers: Dict[str, RecordReader] = {}
            self.inputs: List[tuple] = []    # (reader, lo, hi)
            self.outputs: List[tuple] = []   # (reader, lo, hi, n_classes)

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_input(self, reader: str, col_from: int, col_to: int):
            self.inputs.append((reader, col_from, col_to, None))
            return self

        def add_output(self, reader: str, col_from: int, col_to: int):
            self.outputs.append((reader, col_from, col_to, None))
            return self

        def add_output_one_hot(self, reader: str, col: int, n_classes: int):
            self.outputs.append((reader, col, col, n_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self.inputs or not self.outputs:
                raise ValueError("need at least one input and one output")
            missing = {r for r, *_ in self.inputs + self.outputs} \
                - set(self.readers)
            if missing:
                raise ValueError(f"selections reference unknown readers "
                                 f"{sorted(missing)}")
            return RecordReaderMultiDataSetIterator(self)

    @staticmethod
    def builder(batch_size: int) -> "RecordReaderMultiDataSetIterator.Builder":
        return RecordReaderMultiDataSetIterator.Builder(batch_size)

    def __init__(self, b: "RecordReaderMultiDataSetIterator.Builder"):
        self._b = b

    def batch(self) -> int:
        return self._b.batch_size

    def reset(self) -> None:
        for r in self._b.readers.values():
            r.reset()

    @staticmethod
    def _slice(rows: np.ndarray, lo: int, hi: int,
               n_classes: Optional[int]) -> np.ndarray:
        cols = rows[:, lo:hi + 1].astype(np.float32)
        if n_classes is not None:
            return np.eye(n_classes, dtype=np.float32)[
                cols[:, 0].astype(np.int64)]
        return cols

    def __iter__(self):
        from .dataset import MultiDataSet
        b = self._b
        self.reset()
        streams = {name: iter(r) for name, r in b.readers.items()}
        while True:
            rows: Dict[str, List] = {}
            done = False
            for _ in range(b.batch_size):
                record = {}
                for name, st in streams.items():
                    nxt = next(st, None)
                    if nxt is None:
                        done = True
                        break
                    record[name] = nxt
                if done:
                    break
                for name, vals in record.items():
                    rows.setdefault(name, []).append(vals)
            if not rows:
                return
            mats = {name: np.asarray(v, np.float32)
                    for name, v in rows.items()}
            feats = [self._slice(mats[r], lo, hi, nc)
                     for r, lo, hi, nc in b.inputs]
            labels = [self._slice(mats[r], lo, hi, nc)
                      for r, lo, hi, nc in b.outputs]
            yield MultiDataSet(feats, labels)
            if done:
                return
