"""Record readers + adapters (the DataVec bridge role: reference
``datasets/datavec/RecordReaderDataSetIterator.java``,
``SequenceRecordReaderDataSetIterator.java`` over DataVec's CSV readers).

Record readers yield lists of float records; the iterators assemble them
into DataSets (classification one-hot, regression passthrough, or sequence
tensors with masking for ragged lengths).
"""
from __future__ import annotations

import csv
import os
from pathlib import Path
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .dataset import DataSet, DataSetIterator

__all__ = ["CSVRecordReader", "CSVSequenceRecordReader",
           "CollectionRecordReader", "RecordReaderDataSetIterator",
           "SequenceRecordReaderDataSetIterator"]


class RecordReader:
    """Iterable of per-example records (list of floats)."""

    def __iter__(self) -> Iterator[List[float]]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CSVRecordReader(RecordReader):
    """One record per CSV line (reference DataVec ``CSVRecordReader``)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        with open(self.path, newline="") as fh:
            reader = csv.reader(fh, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield [float(v) for v in row]


class CollectionRecordReader(RecordReader):
    """In-memory records (reference ``CollectionRecordReader``) — test tier."""

    def __init__(self, records: Sequence[Sequence[float]]):
        self.records = [list(map(float, r)) for r in records]

    def __iter__(self):
        return iter([list(r) for r in self.records])


class CSVSequenceRecordReader(RecordReader):
    """One sequence per FILE in a directory (reference DataVec
    ``CSVSequenceRecordReader``); yields [T, n_cols] float arrays."""

    def __init__(self, directory: str, skip_lines: int = 0,
                 delimiter: str = ",", glob: str = "*.csv"):
        self.directory = directory
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.glob = glob

    def __iter__(self):
        for f in sorted(Path(self.directory).glob(self.glob)):
            rows = []
            with open(f, newline="") as fh:
                for i, row in enumerate(csv.reader(fh, delimiter=self.delimiter)):
                    if i < self.skip_lines or not row:
                        continue
                    rows.append([float(v) for v in row])
            yield np.asarray(rows, dtype=np.float32)


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSets (reference
    ``RecordReaderDataSetIterator.java``): ``label_index`` column becomes the
    one-hot label (classification, ``n_classes`` set) or the regression
    target range (``regression=True``, ``label_index_to`` inclusive)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, n_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.n_classes = n_classes
        self.regression = regression
        self.label_index_to = label_index_to
        if not regression and n_classes is None:
            raise ValueError("classification needs n_classes "
                             "(or pass regression=True)")

    def batch(self) -> int:
        return self.batch_size

    def reset(self):
        self.reader.reset()

    def _split(self, rec: List[float]):
        li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
        if self.regression:
            hi = (self.label_index_to if self.label_index_to is not None
                  else li)
            hi = hi if hi >= 0 else len(rec) + hi
            label = rec[li:hi + 1]
            feats = rec[:li] + rec[hi + 1:]
        else:
            label = [rec[li]]
            feats = rec[:li] + rec[li + 1:]
        return feats, label

    def __iter__(self):
        feats, labels = [], []
        for rec in self.reader:
            f, l = self._split(rec)
            feats.append(f)
            labels.append(l)
            if len(feats) == self.batch_size:
                yield self._make(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._make(feats, labels)

    def _make(self, feats, labels):
        x = np.asarray(feats, dtype=np.float32)
        if self.regression:
            y = np.asarray(labels, dtype=np.float32)
        else:
            idx = np.asarray(labels, dtype=np.int64).reshape(-1)
            y = np.eye(self.n_classes, dtype=np.float32)[idx]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Per-file sequences -> padded+masked RNN DataSets (reference
    ``SequenceRecordReaderDataSetIterator`` ALIGN_END=False semantics:
    sequences padded at the END, mask marks valid steps)."""

    def __init__(self, features_reader: CSVSequenceRecordReader,
                 labels_reader: Optional[CSVSequenceRecordReader],
                 batch_size: int, n_classes: Optional[int] = None,
                 regression: bool = False, label_index: int = -1):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self.batch_size = batch_size
        self.n_classes = n_classes
        self.regression = regression
        self.label_index = label_index
        if not regression and n_classes is None:
            raise ValueError("classification needs n_classes "
                             "(or pass regression=True)")

    def batch(self) -> int:
        return self.batch_size

    def _pairs(self):
        if self.labels_reader is not None:
            yield from zip(iter(self.features_reader),
                           iter(self.labels_reader))
        else:  # label column inside the same sequence file
            for seq in self.features_reader:
                li = (self.label_index if self.label_index >= 0
                      else seq.shape[1] + self.label_index)
                lab = seq[:, li:li + 1]
                feat = np.delete(seq, li, axis=1)
                yield feat, lab

    def __iter__(self):
        buf = []
        for pair in self._pairs():
            buf.append(pair)
            if len(buf) == self.batch_size:
                yield self._make(buf)
                buf = []
        if buf:
            yield self._make(buf)

    def _make(self, pairs):
        t_max = max(f.shape[0] for f, _ in pairs)
        n = len(pairs)
        nf = pairs[0][0].shape[1]
        x = np.zeros((n, t_max, nf), np.float32)
        mask = np.zeros((n, t_max), np.float32)
        if self.regression:
            nl = pairs[0][1].shape[1]
            y = np.zeros((n, t_max, nl), np.float32)
        else:
            y = np.zeros((n, t_max, self.n_classes), np.float32)
        for i, (f, l) in enumerate(pairs):
            t = f.shape[0]
            x[i, :t] = f
            mask[i, :t] = 1.0
            if self.regression:
                y[i, :t] = l
            else:
                idx = np.asarray(l, dtype=np.int64).reshape(-1)
                y[i, :t] = np.eye(self.n_classes, dtype=np.float32)[idx]
        return DataSet(x, y, features_mask=mask, labels_mask=mask)
