"""Ecosystem dataset interop.

Reference ``dl4j-spark``'s ``MLLibUtil`` (RDD<LabeledPoint> ↔ DataSet
adapters) — the Python-ecosystem counterpart adapts PyTorch datasets/
dataloaders and (features, labels) pair iterables into our
DataSetIterator protocol, and exposes our iterators back as torch
datasets.  Torch is an optional dependency: importing this module without
torch installed works; only the torch-touching calls require it.
"""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from .dataset import DataSet, DataSetIterator

__all__ = ["TorchDataSetIterator", "from_torch", "as_torch_dataset"]


def _to_numpy(t):
    if hasattr(t, "detach"):
        return t.detach().cpu().numpy()
    return np.asarray(t)


class TorchDataSetIterator(DataSetIterator):
    """Wrap a torch ``DataLoader`` (or any iterable of (x, y) pairs) as a
    DataSetIterator.  One-hot encodes integer class labels when
    ``n_classes`` is given (torch datasets yield class indices; our output
    layers take one-hot)."""

    def __init__(self, loader, n_classes: Optional[int] = None):
        self.loader = loader
        self.n_classes = n_classes

    def batch(self) -> int:
        return getattr(self.loader, "batch_size", -1) or -1

    def reset(self) -> None:
        pass  # DataLoader re-iterates from the top

    def _labels(self, y: np.ndarray) -> np.ndarray:
        if self.n_classes is not None and y.ndim <= 1:
            return np.eye(self.n_classes, dtype=np.float32)[
                y.astype(np.int64).reshape(-1)]
        return y.astype(np.float32)

    def __iter__(self) -> Iterator[DataSet]:
        for batch in self.loader:
            if isinstance(batch, (tuple, list)) and len(batch) >= 2:
                x, y = batch[0], batch[1]
            else:
                raise ValueError(
                    "expected (features, labels) batches from the loader")
            x = _to_numpy(x).astype(np.float32)
            if x.ndim == 4 and x.shape[1] in (1, 3) and \
                    x.shape[1] < x.shape[-1]:
                x = np.transpose(x, (0, 2, 3, 1))  # NCHW (torch) -> NHWC
            yield DataSet(x, self._labels(_to_numpy(y)))


def from_torch(dataset_or_loader, batch_size: int = 32,
               n_classes: Optional[int] = None, shuffle: bool = False
               ) -> TorchDataSetIterator:
    """torch Dataset or DataLoader -> DataSetIterator (builds a DataLoader
    when given a bare Dataset)."""
    if hasattr(dataset_or_loader, "__getitem__") and not hasattr(
            dataset_or_loader, "batch_size"):
        import torch.utils.data as tud
        loader = tud.DataLoader(dataset_or_loader, batch_size=batch_size,
                                shuffle=shuffle)
    else:
        loader = dataset_or_loader
    return TorchDataSetIterator(loader, n_classes=n_classes)


def as_torch_dataset(iterator: DataSetIterator):
    """Our DataSetIterator -> torch IterableDataset (features/labels as
    torch tensors), so torch tooling can consume our pipelines."""
    import torch
    import torch.utils.data as tud

    class _Wrapped(tud.IterableDataset):
        def __iter__(self):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                yield (torch.from_numpy(np.asarray(ds.features)),
                       torch.from_numpy(np.asarray(ds.labels)))

    return _Wrapped()
