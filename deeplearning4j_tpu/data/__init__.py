"""Data pipeline (reference ``deeplearning4j-core/.../datasets/`` +
``deeplearning4j-nn/.../datasets/iterator/``)."""
from .dataset import (AsyncDataSetIterator, BenchmarkDataSetIterator, DataSet,
                      DataSetIterator, EarlyTerminationDataSetIterator,
                      ExistingDataSetIterator, INDArrayDataSetIterator,
                      MovingWindowDataSetIterator, MultipleEpochsIterator,
                      SamplingDataSetIterator)
from .dataset import MultiDataSet
from .records import RecordReaderMultiDataSetIterator
from .dataset import AsyncMultiDataSetIterator
from .dataset import (DataSetCallback, FileSplitDataSetIterator,
                      export_dataset_batches, load_dataset, save_dataset)
from .dataset import (AsyncShieldDataSetIterator,
                      AsyncShieldMultiDataSetIterator, CombinedPreProcessor,
                      DataSetPreProcessor, DoublesDataSetIterator,
                      DummyPreProcessor, FileSplitParallelDataSetIterator,
                      FloatsDataSetIterator, IteratorDataSetIterator,
                      JointParallelDataSetIterator,
                      MultiDataSetWrapperIterator,
                      PreProcessedDataSetIterator,
                      ReconstructionDataSetIterator)
from .pipeline import (DevicePrefetchIterator, MultiprocessETLIterator,
                       build_input_pipeline)
from .shapes import ShapePolicy, default_shape_policy
from .transforms import (ComposeTransform, CutoutTransform,
                         ImageTransform, RandomCropTransform,
                         RandomFlipTransform, TransformingDataSetIterator)
from .normalization import (ImagePreProcessingScaler,
                            NormalizerMinMaxScaler, NormalizerStandardize,
                            load_normalizer)
from .interop import TorchDataSetIterator, as_torch_dataset, from_torch
from .formatter import LocalUnstructuredDataFormatter
from .fetchers import (CifarDataSetIterator, EmnistDataSetIterator,
                       LFWDataSetIterator, TinyImageNetDataSetIterator)
from .mnist import IrisDataSetIterator, MnistDataSetIterator
from .vectorizer import CallableVectorizer, TextCorpusVectorizer, Vectorizer

__all__ = [
    "AsyncDataSetIterator", "AsyncMultiDataSetIterator", "BenchmarkDataSetIterator", "DataSet",
    "DataSetIterator", "EarlyTerminationDataSetIterator",
    "ExistingDataSetIterator", "INDArrayDataSetIterator",
    "IrisDataSetIterator", "MnistDataSetIterator", "MovingWindowDataSetIterator",
    "MultipleEpochsIterator", "SamplingDataSetIterator",
    "CifarDataSetIterator", "EmnistDataSetIterator", "LFWDataSetIterator",
    "TinyImageNetDataSetIterator", "LocalUnstructuredDataFormatter", "DataSetCallback",
    "FileSplitDataSetIterator", "export_dataset_batches", "load_dataset",
    "save_dataset", "TorchDataSetIterator", "as_torch_dataset",
    "from_torch", "MultiDataSet", "RecordReaderMultiDataSetIterator",
    "Vectorizer", "CallableVectorizer", "TextCorpusVectorizer",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler", "load_normalizer", "ImageTransform", "RandomFlipTransform",
    "RandomCropTransform", "CutoutTransform", "ComposeTransform",
    "TransformingDataSetIterator", "AsyncShieldDataSetIterator",
    "AsyncShieldMultiDataSetIterator", "CombinedPreProcessor",
    "DataSetPreProcessor", "DoublesDataSetIterator", "DummyPreProcessor",
    "FileSplitParallelDataSetIterator", "FloatsDataSetIterator",
    "IteratorDataSetIterator", "JointParallelDataSetIterator",
    "MultiDataSetWrapperIterator", "PreProcessedDataSetIterator",
    "ReconstructionDataSetIterator", "DevicePrefetchIterator",
    "MultiprocessETLIterator", "build_input_pipeline",
]
