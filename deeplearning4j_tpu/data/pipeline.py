"""Device-overlapped input pipeline: k-deep device prefetch + multiprocess
shared-memory ETL.

Two composable stages sit between a host ``DataSetIterator`` and the jitted
train step, so neither host ETL nor the host→device copy ever serializes
with device compute (the overlapped-ETL input pipeline of *TensorFlow: A
system for large-scale machine learning*, PAPERS.md):

``MultiprocessETLIterator``
    Worker *processes* run the numpy transform stage (``data/transforms.py``
    et al.) outside the trainer's GIL, handing finished batches back through
    a ring of preallocated shared-memory slabs — a zero-copy handoff (the
    parent yields numpy views straight into the slab; the only host copy is
    the worker writing its result).  Batch order is deterministic and worker
    exceptions propagate to the consumer.

``DevicePrefetchIterator``
    A background thread performs ``jax.device_put`` up to ``depth`` batches
    ahead of the consumer — replicated on the default device, or sharded over
    a mesh via ``NamedSharding`` so ``ParallelWrapper``/SPMD training gets
    per-device placement for free.  The H2D copy of batch *n+k* overlaps the
    in-flight step for batch *n* instead of being paid inside it;
    ``MultiLayerNetwork.fit`` / ``ParallelWrapper.fit`` detect the already
    device-resident arrays and skip re-placement.

Observability (rides the PR-2 registry; all instruments resolved once per
iteration, never forcing a device sync):

- ``training_etl_seconds{stage}`` histogram — per-stage waits:
  ``fetch`` (trainer blocked on the iterator — recorded by ``fit``),
  ``source``/``h2d`` (prefetch producer pulling + placing),
  ``wait`` (consumer blocked on the device queue),
  ``transform`` (worker ETL time, measured in-worker, observed parent-side),
  ``ring`` (parent blocked on the shared-memory ring).
- ``training_pipeline_depth{stage=device|ring}`` gauges — how full each
  stage's buffer is (a healthy overlapped pipeline sits near its depth).
- ``training_pipeline_starved_total{stage=device|ring}`` counters — times a
  consumer found the buffer empty (the producer is the bottleneck).
"""
from __future__ import annotations

import multiprocessing
import queue
import threading
import time
import traceback
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import AsyncShieldDataSetIterator, DataSet, DataSetIterator
from ..observability.clock import monotonic_s
from ..observability.registry import default_registry

__all__ = ["DevicePrefetchIterator", "MultiprocessETLIterator",
           "build_input_pipeline", "ETL_BUCKETS"]

# training_etl_seconds bucket bounds — shared with nn/multilayer.py's
# registration of the same family (the registry rejects re-registration
# with different buckets, so there must be exactly one source of truth).
ETL_BUCKETS: Tuple[float, ...] = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_FIELDS = ("features", "labels", "features_mask", "labels_mask")

# slabs whose close() found live consumer views at teardown: kept referenced
# so SharedMemory.__del__ never re-raises mid-GC; already unlinked, so the
# OS frees the memory with the last unmap (normally empty — slabs close
# cleanly when consumers drop batches before finishing the iterator)
_UNCLOSED_SLABS: List = []


def _etl_instruments(registry=None):
    """(etl_histogram, depth_gauge, starved_counter) or (None,)*3 when the
    registry is disabled — callers hold the instruments for the whole
    iteration so the hot path is one labels() lookup + plain float math."""
    reg = registry if registry is not None else default_registry()
    if not reg.enabled:
        return None, None, None
    etl = reg.histogram(
        "training_etl_seconds",
        "Time blocked on the data pipeline per batch, by stage",
        ("stage",), buckets=ETL_BUCKETS)
    depth = reg.gauge("training_pipeline_depth",
                      "Batches buffered ahead of the consumer, by stage",
                      ("stage",))
    starved = reg.counter("training_pipeline_starved_total",
                          "Times a pipeline consumer found its buffer empty",
                          ("stage",))
    return etl, depth, starved


# ===================================================================== device
class DevicePrefetchIterator(DataSetIterator):
    """Wrap any ``DataSetIterator`` and ``jax.device_put`` up to ``depth``
    batches ahead on a background thread.

    With ``mesh=None`` batches land committed on the default device.  With a
    ``jax.sharding.Mesh``, each array is placed with a ``NamedSharding``
    whose leading axis maps to ``data_axis`` (optionally a time axis to
    ``seq_axis``), and partial batches are trimmed to a multiple of the
    data-axis size — the same policy as ``ParallelWrapper._trim``, so the
    wrapper sees only evenly-divisible device-resident batches and skips
    both trim and re-placement.

    The yielded ``DataSet`` holds ``jax.Array`` leaves.  Downstream jitted
    steps never donate batch arguments (only params/state/opt_state), so a
    prefetched buffer is never invalidated by the step that consumes it.
    Not re-entrant: one live iteration at a time (a second concurrent
    ``__iter__`` raises rather than racing two producers over the
    underlying iterator).
    """

    _SENTINEL = object()

    def __init__(self, underlying: DataSetIterator, depth: int = 2, *,
                 mesh=None, data_axis: str = "data",
                 seq_axis_name: Optional[str] = None,
                 seq_axis: Optional[int] = None, registry=None):
        if isinstance(underlying, AsyncShieldDataSetIterator):
            raise ValueError(
                "iterator is wrapped in AsyncShieldDataSetIterator — it must "
                "not be prefetched from a background thread")
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.underlying = underlying
        self.depth = depth
        self.mesh = mesh
        self.data_axis = data_axis
        self.seq_axis_name = seq_axis_name
        self.seq_axis = seq_axis
        self._registry = registry
        self._state_lock = threading.Lock()
        self._active = False

    def batch(self):
        return self.underlying.batch()

    def reset(self):
        self.underlying.reset()

    # ------------------------------------------------------------ placement
    def _data_axis_size(self) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape.get(self.data_axis, 1))

    def _sharding_for(self, ndim: int):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        spec = [None] * ndim
        if ndim > 0:
            spec[0] = self.data_axis
        if (self.seq_axis_name is not None and self.seq_axis is not None
                and ndim > self.seq_axis):
            spec[self.seq_axis] = self.seq_axis_name
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def _place(self, ds) -> Optional[DataSet]:
        """Host batch -> device-resident DataSet (None: sub-shard batch)."""
        import jax
        fields = [getattr(ds, f, None) for f in _FIELDS] \
            if not isinstance(ds, (tuple, list)) else \
            list(ds) + [None] * (4 - len(ds))
        d = self._data_axis_size()
        if d > 1:
            n = int(np.shape(fields[0])[0])
            keep = (n // d) * d
            if keep == 0:
                return None                    # smaller than the data axis
            if keep != n:
                fields = [None if a is None else a[:keep] for a in fields]
        out = []
        for a in fields:   # per-field, not per-step: this IS the prefetch stage
            if a is None:
                out.append(None)
            elif self.mesh is None:
                out.append(a if isinstance(a, jax.Array)
                           else jax.device_put(a))  # graftlint: disable=JX012
            else:
                out.append(jax.device_put(  # graftlint: disable=JX012
                    a, self._sharding_for(np.ndim(a))))
        return DataSet(*out)

    # ------------------------------------------------------------ iteration
    def __iter__(self):
        with self._state_lock:
            if self._active:
                raise RuntimeError(
                    "DevicePrefetchIterator is already being iterated — a "
                    "second concurrent iteration would race two producer "
                    "threads over one underlying iterator")
            self._active = True
        try:
            yield from self._run()
        finally:
            with self._state_lock:
                self._active = False

    def _run(self):
        etl_h, depth_g, starved_c = _etl_instruments(self._registry)
        # per-stage children resolved once, off the per-batch path (JX022)
        if etl_h is not None:
            src_h, h2d_h, wait_h = (etl_h.labels("source"),
                                    etl_h.labels("h2d"),
                                    etl_h.labels("wait"))
            depth_dev = depth_g.labels("device")
            starved_dev = starved_c.labels("device")
        else:
            src_h = h2d_h = wait_h = depth_dev = starved_dev = None
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        err: List[BaseException] = []

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                it = iter(self.underlying)
                while True:
                    t0 = monotonic_s()
                    try:
                        ds = next(it)
                    except StopIteration:
                        break
                    t1 = monotonic_s()
                    # the device_put inside _place is ASYNC dispatch (it
                    # enqueues the H2D copy) — the histogram records
                    # host-side cost, the transfer overlaps the in-flight step
                    placed = self._place(ds)
                    t2 = monotonic_s()
                    if src_h is not None:
                        src_h.observe(t1 - t0)
                        h2d_h.observe(t2 - t1)
                    if placed is None:
                        continue
                    if not _put(placed):
                        return                 # consumer went away
                    if depth_dev is not None:
                        depth_dev.set(q.qsize())
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                err.append(e)
            finally:
                _put(self._SENTINEL)

        t = threading.Thread(target=producer, daemon=True,
                             name="device-prefetch")
        t.start()
        first_get = True
        try:
            while True:
                # the very first get is empty by construction (producer
                # warm-up), not a starvation signal
                if starved_dev is not None and q.empty() and not first_get:
                    starved_dev.inc()
                first_get = False
                t0 = monotonic_s()
                item = q.get()
                if wait_h is not None:
                    wait_h.observe(monotonic_s() - t0)
                    depth_dev.set(q.qsize())
                if item is self._SENTINEL:
                    break
                yield item
        finally:
            stop.set()
            t.join()
        if err:
            raise err[0]


# ================================================================ multiproc
def _mute_shm_tracking() -> None:
    """Stop THIS process's resource tracker from adopting shared-memory
    attachments.  In CPython < 3.13 ``SharedMemory(name=...)`` registers on
    *attach* too, so a worker would co-own (and at exit unregister/unlink)
    slabs the parent created and still owns — the parent's own unlink then
    double-unregisters in the shared tracker process.  Workers are dedicated
    processes, so the patch is process-wide and never reverted."""
    from multiprocessing import resource_tracker
    orig = resource_tracker.register

    def register(name, rtype):
        if rtype != "shared_memory":
            orig(name, rtype)

    resource_tracker.register = register


def _attach_shm(name: str):
    from multiprocessing import shared_memory
    return shared_memory.SharedMemory(name=name)


def _etl_worker(worker_id: int, num_workers: int, source_factory,
                transform, seed: int, epoch: int, slot_names: Sequence[str],
                slot_bytes: int, slots_per_worker: int, sem, result_q,
                stop_evt) -> None:
    """Worker-process body: iterate a private copy of the source, process
    the interleaved shard ``seq % num_workers == worker_id``, write results
    into this worker's ring slots.  The ETL itself is pure numpy; jax is
    pinned to cpu up front so user code inside ``source_factory``/
    ``transform`` can never dial the training accelerator (env changes are
    too late for that — the config update is the reliable mechanism)."""
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _mute_shm_tracking()
    shms = [_attach_shm(slot_names[worker_id * slots_per_worker + i])
            for i in range(slots_per_worker)]
    local = 0
    try:
        source = source_factory()
        for _ in range(epoch):
            # replay resets so per-epoch source state (shuffle streams)
            # matches a single-process consumer on the same epoch
            if hasattr(source, "reset"):
                source.reset()
        for seq, ds in enumerate(source):
            if stop_evt.is_set():
                return
            if seq % num_workers != worker_id:
                continue
            t0 = time.perf_counter()
            fields = [None if a is None else np.asarray(a)
                      for a in (ds.features, ds.labels,
                                getattr(ds, "features_mask", None),
                                getattr(ds, "labels_mask", None))]
            if transform is not None:
                rng = np.random.default_rng((seed, epoch, seq))
                fields[0] = np.ascontiguousarray(transform(fields[0], rng))
            etl_s = time.perf_counter() - t0
            payload = [(f, None if a is None else np.ascontiguousarray(a))
                       for f, a in zip(_FIELDS, fields)]
            nbytes = sum(a.nbytes for _, a in payload if a is not None)
            if nbytes <= slot_bytes:
                # wait for one of OUR slots to be released by the parent;
                # stop-aware so shutdown never deadlocks on a full ring
                while not stop_evt.is_set():
                    if sem.acquire(timeout=0.1):
                        break
                else:
                    return
                shm = shms[local % slots_per_worker]
                meta, off = [], 0
                for fname, a in payload:
                    if a is None:
                        continue
                    shm.buf[off:off + a.nbytes] = a.tobytes()
                    meta.append((fname, a.shape, a.dtype.str, off))
                    off += a.nbytes
                result_q.put(("slab", seq, worker_id,
                              local % slots_per_worker, etl_s, meta))
                local += 1
            else:
                # batch outgrew the preallocated slab (variable-shape
                # transform): fall back to a pickled handoff for this batch
                result_q.put(("inline", seq, worker_id, None, etl_s,
                              {f: a for f, a in payload if a is not None}))
    except BaseException:  # noqa: BLE001 - relayed to the parent
        result_q.put(("error", worker_id, traceback.format_exc()))
    finally:
        result_q.put(("done", worker_id))
        for shm in shms:
            try:
                shm.close()
            except BufferError:
                pass


class MultiprocessETLIterator(DataSetIterator):
    """Run host ETL (the numpy transform stage) in worker *processes*,
    handing finished batches back through a preallocated shared-memory ring.

    Each worker builds its own source from ``source_factory`` (a picklable
    zero-argument callable returning a ``DataSetIterator``), iterates it, and
    fully processes only the interleaved shard ``seq % num_workers ==
    worker_id`` — the *transform* (the expensive part, e.g. a
    ``data/transforms.ImageTransform``) is what escapes the GIL; the cheap
    source iteration is replayed per worker to keep batch order
    deterministic without inter-process coordination.  ``transform(features,
    rng) -> features`` runs under ``np.random.default_rng((seed, epoch,
    seq))`` so results are reproducible regardless of worker count or
    scheduling.

    Ring protocol: every worker owns ``slots_per_worker`` shared-memory
    slabs used cyclically; a semaphore per worker counts free slots.  The
    parent reorders arrivals by sequence number (deterministic order) and
    yields ``DataSet`` batches.  The worker→parent handoff is always
    through shared memory (no pickling); with the default
    ``copy_out=True`` the parent materializes each batch out of the slab
    (one memcpy) and frees the slot immediately — batches are then plain
    owned arrays, safe to stash or hand to an async device-prefetch
    stage.  ``copy_out=False`` removes even that memcpy: batches are
    ZERO-COPY views into the slab, valid only until the next ``next()``
    — the caller must consume each batch synchronously (and beware that
    ``jax.device_put`` on the CPU backend may *alias* rather than copy an
    aligned view: never combine ``copy_out=False`` with a prefetch queue
    that outlives the slot).  A batch that outgrows its slab
    (variable-shape transform) silently falls back to a pickled handoff.

    Workers are spawned (never forked: the parent may hold jax/TPU state
    and live threads) and pin jax to the cpu platform first thing
    (``jax.config.update``), so worker-side jax use can never dial the
    training accelerator.  Worker exceptions propagate to the consumer as
    ``RuntimeError`` carrying the worker traceback; shutdown (normal end,
    consumer break, or error) stops workers, joins them, and unlinks
    every slab.
    """

    def __init__(self, source_factory: Callable[[], DataSetIterator],
                 transform=None, *, num_workers: int = 2,
                 slots_per_worker: int = 2, slot_bytes: Optional[int] = None,
                 seed: int = 0, copy_out: bool = True, registry=None,
                 join_timeout_s: float = 10.0):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if slots_per_worker < 1:
            raise ValueError(
                f"slots_per_worker must be >= 1, got {slots_per_worker}")
        self.source_factory = source_factory
        self.transform = transform
        self.num_workers = num_workers
        self.slots_per_worker = slots_per_worker
        self.slot_bytes = slot_bytes
        self.seed = seed
        self.copy_out = copy_out
        self.join_timeout_s = join_timeout_s
        self._registry = registry
        self._epoch = 0
        self._batch: Optional[int] = None
        self._state_lock = threading.Lock()
        self._active = False

    def batch(self):
        if self._batch is None:
            self._batch = int(self.source_factory().batch())
        return self._batch

    def reset(self):
        self._epoch += 1

    # ------------------------------------------------------------ internals
    def _probe_slot_bytes(self) -> int:
        """Size slabs from the first (transformed) batch of a parent-side
        probe source; later batches are at most this big for standard
        iterators (only the final batch shrinks), and bigger ones fall back
        to the inline path.  The result is cached on ``slot_bytes`` so
        re-iteration (one ring per epoch) doesn't rebuild the source and
        re-run the transform every time."""
        if self.slot_bytes is not None:
            return int(self.slot_bytes)
        probe = next(iter(self.source_factory()), None)
        if probe is None:
            self.slot_bytes = 1
            return 1
        fields = [None if a is None else np.asarray(a)
                  for a in (probe.features, probe.labels,
                            getattr(probe, "features_mask", None),
                            getattr(probe, "labels_mask", None))]
        if self.transform is not None:
            rng = np.random.default_rng((self.seed, 0, 0))
            fields[0] = np.asarray(self.transform(fields[0], rng))
        self.slot_bytes = max(1, sum(a.nbytes for a in fields
                                     if a is not None))
        return self.slot_bytes

    def __iter__(self):
        with self._state_lock:
            if self._active:
                raise RuntimeError(
                    "MultiprocessETLIterator is already being iterated — a "
                    "second concurrent iteration would tear down the ring "
                    "under the first one")
            self._active = True
        try:
            yield from self._run()
        finally:
            with self._state_lock:
                self._active = False

    def _run(self):
        from multiprocessing import shared_memory
        etl_h, depth_g, starved_c = _etl_instruments(self._registry)
        # per-stage children resolved once, off the per-batch path (JX022)
        if etl_h is not None:
            ring_h = etl_h.labels("ring")
            transform_h = etl_h.labels("transform")
            depth_ring = depth_g.labels("ring")
            starved_ring = starved_c.labels("ring")
        else:
            ring_h = transform_h = depth_ring = starved_ring = None
        ctx = multiprocessing.get_context("spawn")
        slot_bytes = self._probe_slot_bytes()
        n_slots = self.num_workers * self.slots_per_worker
        shms = [shared_memory.SharedMemory(create=True, size=slot_bytes)
                for _ in range(n_slots)]
        slot_names = [s.name for s in shms]
        sems = [ctx.Semaphore(self.slots_per_worker)
                for _ in range(self.num_workers)]
        result_q = ctx.Queue()
        stop_evt = ctx.Event()
        workers = [
            ctx.Process(
                target=_etl_worker,
                args=(w, self.num_workers, self.source_factory,
                      self.transform, self.seed, self._epoch, slot_names,
                      slot_bytes, self.slots_per_worker, sems[w],
                      result_q, stop_evt),
                daemon=True, name=f"etl-worker-{w}")
            for w in range(self.num_workers)]
        for p in workers:
            p.start()
        pending_release: Optional[int] = None   # worker whose slot we hold

        def _release_prev():
            nonlocal pending_release
            if pending_release is not None:
                sems[pending_release].release()
                pending_release = None

        try:
            buffer = {}
            next_seq = 0
            done = 0
            failure: Optional[str] = None
            while True:
                starved_counted = False
                while next_seq not in buffer:
                    if done >= self.num_workers:
                        break
                    # at most one starvation event per awaited batch, not
                    # one per 0.5 s poll cycle
                    if (starved_ring is not None and not starved_counted
                            and result_q.empty()):
                        starved_ring.inc()
                        starved_counted = True
                    t0 = monotonic_s()
                    try:
                        msg = result_q.get(timeout=0.5)
                    except queue.Empty:
                        if not any(p.is_alive() for p in workers):
                            done = self.num_workers
                            failure = failure or (
                                "ETL worker(s) died without reporting. If "
                                "this happened at startup, make sure the "
                                "program's entry point is guarded with "
                                "`if __name__ == '__main__':` — "
                                "multiprocessing spawn re-imports the main "
                                "module (see the worker stderr above)")
                        continue
                    if ring_h is not None:
                        ring_h.observe(monotonic_s() - t0)
                    kind = msg[0]
                    if kind == "done":
                        done += 1
                    elif kind == "error":
                        failure = f"ETL worker {msg[1]} failed:\n{msg[2]}"
                        stop_evt.set()
                    else:
                        buffer[msg[1]] = msg
                        if depth_ring is not None:
                            depth_ring.set(len(buffer))
                if next_seq not in buffer:
                    break
                kind, seq, wid, slot, etl_s, payload = buffer.pop(next_seq)
                if transform_h is not None:
                    transform_h.observe(etl_s)
                    depth_ring.set(len(buffer))
                if kind == "slab":
                    shm = shms[wid * self.slots_per_worker + slot]
                    arrays = {}
                    for fname, shape, dtype, off in payload:
                        count = int(np.prod(shape)) if shape else 1
                        view = np.frombuffer(
                            shm.buf, dtype=np.dtype(dtype), count=count,
                            offset=off).reshape(shape)
                        # copy_out: one memcpy buys an OWNED batch — the
                        # slot recycles immediately and nothing downstream
                        # (a prefetch queue, a zero-copy device_put alias
                        # on the CPU backend) can observe the worker's
                        # next write to this slab
                        arrays[fname] = np.array(view) if self.copy_out \
                            else view
                    if self.copy_out:
                        sems[wid].release()
                        yield DataSet(*[arrays.get(f) for f in _FIELDS])
                    else:
                        _release_prev()
                        ds = DataSet(*[arrays.get(f) for f in _FIELDS])
                        arrays = None  # frame must not pin slab views past
                        yield ds       # the consumer's lifetime for them
                        ds = None
                        pending_release = wid
                else:                               # inline fallback
                    _release_prev()
                    yield DataSet(*[payload.get(f) for f in _FIELDS])
                next_seq += 1
            if failure is not None:
                raise RuntimeError(failure)
        finally:
            stop_evt.set()
            _release_prev()
            for p in workers:
                p.join(timeout=self.join_timeout_s)
            for p in workers:
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=1.0)
            result_q.cancel_join_thread()
            result_q.close()
            for s in shms:
                try:
                    s.close()
                except BufferError:
                    # the consumer still holds a zero-copy view into this
                    # slab (documented: views live until the next next()).
                    # Keep the object referenced so __del__ never re-raises;
                    # the mapping is freed when the process exits.
                    _UNCLOSED_SLABS.append(s)
                try:
                    s.unlink()
                except FileNotFoundError:
                    pass


# ================================================================= pipeline
def build_input_pipeline(source_factory: Callable[[], DataSetIterator],
                         transform=None, *, num_workers: int = 2,
                         depth: int = 2, mesh=None, seed: int = 0,
                         registry=None) -> DevicePrefetchIterator:
    """The full overlapped pipeline in one call: multiprocess ETL feeding a
    k-deep device prefetch.  ``num_workers=0`` skips the multiprocess stage
    (the source runs on the prefetch thread — the right choice when the
    transform is cheap or the source is not picklable)."""
    if num_workers > 0:
        inner: DataSetIterator = MultiprocessETLIterator(
            source_factory, transform, num_workers=num_workers, seed=seed,
            registry=registry)
    else:
        inner = source_factory()
        if transform is not None:
            from .transforms import TransformingDataSetIterator
            inner = TransformingDataSetIterator(inner, transform, seed=seed)
    return DevicePrefetchIterator(inner, depth, mesh=mesh, registry=registry)
