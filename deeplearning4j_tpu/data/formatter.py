"""Raw-directory → train/test split formatter.

Reference ``deeplearning4j-core/.../datasets/rearrange/
LocalUnstructuredDataFormatter.java``: takes an unstructured labeled image
dir (``root/<label>/file``) and rearranges it into
``split/train/<label>/…`` + ``split/test/<label>/…`` by a test fraction.
"""
from __future__ import annotations

import shutil
from pathlib import Path
from typing import List, Optional

import numpy as np

__all__ = ["LocalUnstructuredDataFormatter"]


class LocalUnstructuredDataFormatter:
    """Deterministic (seeded) per-label split; files are copied (the
    reference moves, copying keeps the source intact — pass move=True for
    parity)."""

    def __init__(self, dest_dir, src_dir, test_fraction: float = 0.2,
                 seed: int = 123, move: bool = False):
        if not 0.0 <= test_fraction < 1.0:
            raise ValueError(f"test_fraction must be in [0,1), got "
                             f"{test_fraction}")
        self.dest = Path(dest_dir)
        self.src = Path(src_dir)
        self.test_fraction = test_fraction
        self.seed = seed
        self.move = move
        self.num_examples_total = 0
        self.num_test = 0

    def rearrange(self) -> None:
        if not self.src.is_dir():
            raise FileNotFoundError(f"source dir {self.src} does not exist")
        rng = np.random.default_rng(self.seed)
        for label_dir in sorted(p for p in self.src.iterdir() if p.is_dir()):
            files: List[Path] = sorted(
                p for p in label_dir.iterdir() if p.is_file())
            if not files:
                continue
            order = rng.permutation(len(files))
            n_test = int(round(len(files) * self.test_fraction))
            test_idx = set(order[:n_test].tolist())
            for i, f in enumerate(files):
                split = "test" if i in test_idx else "train"
                target = self.dest / "split" / split / label_dir.name
                target.mkdir(parents=True, exist_ok=True)
                if self.move:
                    shutil.move(str(f), target / f.name)
                else:
                    shutil.copy2(f, target / f.name)
                self.num_examples_total += 1
                self.num_test += split == "test"

    def get_num_examples_total(self) -> int:
        return self.num_examples_total

    def get_num_examples_to_train_on(self) -> int:
        return self.num_examples_total - self.num_test

    def get_num_test_examples(self) -> int:
        return self.num_test
