"""Static-shape batch bucketing: pad ragged batches onto compiled shapes.

XLA compiles one executable per input shape, so a partial final batch or a
novel RNN sequence length retraces the whole train step.  ``ShapePolicy``
pads such batches up to a *bucket* — a shape the process has already
compiled (``auto`` mode) or a fixed ladder (explicit buckets / powers of
two, mirroring ``ParallelInference``'s inference-side buckets) — and masks
the padded rows out of the loss through the train step's existing
``label_mask`` argument, so the padded step is numerically identical to the
unpadded one (loss denominators count only rows whose mask has any weight;
see ``nn/losses._apply_mask_and_mean``).

Padded ROWS repeat the batch's last real row (keeps every forward op
well-conditioned: no zero-mask divisions, no degenerate statistics) and
carry a zero label mask; padded TIMESTEPS (explicit-bucket/pow2 modes only)
are zero-masked in both the feature and label masks, the same convention
variable-length sequence batches already use.

Known caveats (the networks gate on these — ``_pad_flags``): padding is
skipped entirely for AUX_LOSS stacks (MoE: padded rows compete for expert
capacity even at inference, and the whole-batch load-balancing term
defeats the label mask), for loss paths whose head ignores masks (YOLO),
and for training when the stack contains a cross-batch layer
(BatchNormalization trains on batch statistics, which padded rows would
perturb — eval uses running statistics and stays safe).  Recurring eval
paths additionally cap padding waste at 8x the real batch (auto mode).

**Cost model (auto mode, training paths)**: whether to pad a batch of
size n onto an already-compiled bucket t is a rent-vs-buy decision —
padding "rents" the big bucket at ``step_seconds x (t-n)/n`` extra
compute per step (padded_flops/real_flops is linear in rows on the batch
axis), compiling n natively "buys" a ``compile_seconds`` one-off.  The
policy tracks how often each REAL size recurs per (path, axis) and pads
only while the projected cumulative padding waste stays below the
amortized recompile cost (the classic ski-rental rule: total overhead is
bounded by ~2x one compile).  A one-off ragged epoch tail therefore
always pads; a steadily recurring small shape gets its own compile after
a bounded number of padded steps — which is exactly the s=128 class of
regression (BENCH_SIDE r05: auto 36% slower than off) this model fixes.
Compile/step costs come from the live observability registry
(``training_compile_seconds`` / ``training_step_seconds{phase=steady}``)
with env-overridable priors (``DL4J_TPU_PAD_COMPILE_S``,
``DL4J_TPU_PAD_STEP_S``, bias ``DL4J_TPU_PAD_RECOMPILE_BIAS``).

The per-(path, axis) bucket ladder is LRU-bounded
(``DL4J_TPU_SHAPE_BUCKET_CAP``, default 16) so long multi-shape runs
can't grow dispatch history without limit; ``training_shape_buckets``,
``training_padding_ratio`` and ``training_padding_skipped_total`` expose
the ladder size, the realized padding waste, and declined pads in
/metrics.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["ShapePolicy", "default_shape_policy", "next_pow2",
           "serving_buckets", "prefill_buckets", "suffix_prefill_buckets"]

# padded/real element ratios: 1.0 = no padding, right tail = pathological
_RATIO_BUCKETS = (1.0, 1.05, 1.1, 1.25, 1.5, 2.0, 3.0, 4.0, 8.0, 16.0)


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def serving_buckets(max_batch: int,
                    ladder: Optional[Sequence[int]] = None) -> list:
    """The inference-side batch-bucket ladder: powers of two capped by
    ``max_batch`` (which is always the top bucket, pow2 or not).

    ONE definition shared by ``ParallelInference`` and the serving
    engine, so every serving path dispatches the same compiled shape set
    — a request padded here rides an executable some other front-end
    already compiled, and steady-state serving stays at zero new XLA
    compiles beyond this ladder.  An explicit ``ladder`` is respected
    as-is (sorted, deduplicated).
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if ladder:
        return sorted({int(b) for b in ladder})
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b <<= 1
    return out + [int(max_batch)]


def prefill_buckets(max_len: int,
                    ladder: Optional[Sequence[int]] = None,
                    min_bucket: int = 8) -> list:
    """The generation-side prompt-length ladder: powers of two from
    ``min_bucket`` capped by ``max_len`` (which is always the top bucket,
    pow2 or not).

    This is the decode twin of :func:`serving_buckets`, bucketing the
    TIME axis instead of the batch axis: a ragged prompt pads up to the
    smallest bucket that holds it and rides a prefill program compiled
    at warmup, so steady-state generation never traces a novel prompt
    shape.  The ladder tops out at the engine's full cache capacity
    because mid-flight weight migration re-prefills a sequence from its
    complete history — the top bucket must hold the longest sequence the
    cache can, not just the longest *prompt* admission allows.  An
    explicit ``ladder`` is respected as-is (sorted, deduplicated,
    capped entries dropped).
    """
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    if ladder:
        out = sorted({int(b) for b in ladder if int(b) <= max_len})
        if not out:
            raise ValueError(f"explicit ladder {list(ladder)} has no "
                             f"bucket <= max_len {max_len}")
        if out[-1] != max_len:
            out.append(int(max_len))
        return out
    out = []
    b = max(1, int(min_bucket))
    while b < max_len:
        out.append(b)
        b <<= 1
    return out + [int(max_len)]


def suffix_prefill_buckets(max_len: int, block_size: int,
                           ladder: Optional[Sequence[int]] = None) -> list:
    """Prefill ladder for the PAGED engine, bucketing the *unshared
    suffix* length rather than the whole prompt: a shared-prefix
    admission runs only its suffix through the prefill program, so short
    suffixes should ride small buckets instead of padding up to the full
    prompt bucket.  The floor is the KV block size (a matched prefix
    always ends on a block or COW boundary, so suffixes shorter than one
    block are common); the top stays ``max_len`` because a cold prompt —
    or a hot-swap migration re-prefilling a full history — is just a
    suffix of length L with nothing shared.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    return prefill_buckets(max_len, ladder,
                           min_bucket=min(8, int(block_size)))


def _pad_rows(a, pad: int, zero: bool = False):
    """Append ``pad`` rows: copies of the last real row, or zeros."""
    import jax.numpy as jnp
    a = jnp.asarray(a)
    tail = jnp.zeros_like(a[-1:]) if zero else a[-1:]
    return jnp.concatenate([a] + [tail] * pad, axis=0)


def _pad_time(a, pad: int):
    """Append ``pad`` zero timesteps on axis 1."""
    import jax.numpy as jnp
    a = jnp.asarray(a)
    shape = list(a.shape)
    shape[1] = pad
    return jnp.concatenate([a, jnp.zeros(shape, a.dtype)], axis=1)


class ShapePolicy:
    """Pad-to-bucket policy for one network.

    Modes:
      - ``auto`` (default): pad a batch up to the smallest batch size this
        policy has already dispatched on the same path — the ragged *final*
        batch of an epoch rides the steady batch's compiled executable.
        Never pads the first/largest shape, so uniform workloads are
        untouched.  Batch axis only.
      - ``pow2``: pad the batch axis to the next power of two; 3-D inputs
        also pad the time axis to the next power of two.
      - ``buckets``: explicit ladders (``batch_buckets`` required,
        ``time_buckets`` optional); a size beyond the top bucket passes
        through unpadded (one compile, same as today).
      - ``off``: disabled.

    Thread-safe: the training masters drive replicas from worker threads.
    """

    #: when the registry has no measurement yet, assume a compile costs
    #: this many seconds and a steady step this many — overridable priors
    DEFAULT_COMPILE_S = 2.0
    DEFAULT_STEP_S = 0.02

    def __init__(self, mode: str = "auto",
                 batch_buckets: Optional[Sequence[int]] = None,
                 time_buckets: Optional[Sequence[int]] = None,
                 max_buckets: Optional[int] = None,
                 compile_cost_s: Optional[float] = None,
                 step_cost_s: Optional[float] = None):
        if mode not in ("auto", "pow2", "buckets", "off"):
            raise ValueError(f"unknown shape-policy mode '{mode}'")
        if mode == "buckets" and not batch_buckets:
            raise ValueError("mode='buckets' needs batch_buckets")
        self.mode = mode
        self.batch_buckets = sorted(int(b) for b in batch_buckets) \
            if batch_buckets else None
        self.time_buckets = sorted(int(b) for b in time_buckets) \
            if time_buckets else None
        self.max_buckets = int(max_buckets) if max_buckets else int(
            os.environ.get("DL4J_TPU_SHAPE_BUCKET_CAP", "16"))
        self.last_pad_ratio = 1.0
        # fixed cost overrides (tests / operators); None = live estimate
        # from the metrics registry with env-default priors
        self._compile_cost_s = compile_cost_s
        self._step_cost_s = step_cost_s
        self._recompile_bias = float(
            os.environ.get("DL4J_TPU_PAD_RECOMPILE_BIAS", "1.0"))
        # LRU ladders of DISPATCHED (compiled) sizes, oldest first, capped
        # at max_buckets per (path, axis)
        self._buckets: Dict[Tuple[str, str], "OrderedDict[int, None]"] = {}
        # recency-bounded histogram of REQUESTED sizes (the cost model's
        # recurrence evidence), capped at 4x the bucket cap
        self._hist: Dict[Tuple[str, str], "OrderedDict[int, int]"] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # -------------------------------------------------------- observability
    @staticmethod
    def _registry():
        from ..observability.registry import default_registry
        return default_registry()

    def _note_skip(self, path: str) -> None:
        reg = self._registry()
        if reg.enabled:
            reg.counter("training_padding_skipped_total",
                        "Pads declined by the cost model / eval cap "
                        "(the batch dispatched at its native size)",
                        ("path",)).labels(path).inc()

    def _note_ratio(self, path: str, ratio: float) -> None:
        # cheap host-side copy of the most recent padded/real ratio: the
        # health monitor's padding-drift detector reads it per step
        # without a registry round-trip
        self.last_pad_ratio = float(ratio)
        reg = self._registry()
        if reg.enabled:
            reg.histogram("training_padding_ratio",
                          "Padded/real element ratio per dispatched batch "
                          "(1.0 = no padding)", ("path",),
                          buckets=_RATIO_BUCKETS).labels(path).observe(ratio)

    def _costs(self) -> Tuple[float, float]:
        """(compile_seconds, steady_step_seconds) — measured averages from
        the live registry where available, else env-overridable priors."""
        compile_s, step_s = self._compile_cost_s, self._step_cost_s
        if compile_s is not None and step_s is not None:
            return compile_s, step_s
        reg = self._registry()

        def avg(name, want_labels, default):
            inst = reg.get(name) if reg.enabled else None
            if inst is None or not hasattr(inst, "samples"):
                return default
            tot = cnt = 0.0
            for labels, child in inst.samples():
                if want_labels is not None and labels != want_labels:
                    continue
                tot += getattr(child, "sum", 0.0)
                cnt += getattr(child, "count", 0)
            return tot / cnt if cnt else default

        if compile_s is None:
            compile_s = avg("training_compile_seconds", None, float(
                os.environ.get("DL4J_TPU_PAD_COMPILE_S",
                               str(self.DEFAULT_COMPILE_S))))
        if step_s is None:
            step_s = avg("training_step_seconds", ("steady",), float(
                os.environ.get("DL4J_TPU_PAD_STEP_S",
                               str(self.DEFAULT_STEP_S))))
        return compile_s, step_s

    # ------------------------------------------------------------ targets
    def _note_dispatch(self, path: str, axis: str, size: int) -> None:
        """Record a dispatched size in the LRU ladder (lock held)."""
        od = self._buckets.setdefault((path, axis), OrderedDict())
        od.pop(size, None)
        od[size] = None
        while len(od) > self.max_buckets:
            od.popitem(last=False)
        reg = self._registry()
        if reg.enabled:
            total = sum(len(v) for (p, _a), v in self._buckets.items()
                        if p == path)
            reg.gauge("training_shape_buckets",
                      "Live dispatched-shape buckets per path (LRU-capped "
                      "at DL4J_TPU_SHAPE_BUCKET_CAP per axis)",
                      ("path",)).labels(path).set(total)

    def _target(self, path: str, axis: str, n: int) -> int:
        if self.mode == "off" or n <= 0:
            return n
        if self.mode == "buckets":
            ladder = self.batch_buckets if axis == "batch" \
                else self.time_buckets
            if not ladder:
                return n
            for b in ladder:
                if n <= b:
                    return b
            return n  # beyond top bucket: dispatch unpadded
        if self.mode == "pow2":
            return next_pow2(n)
        # auto: smallest already-dispatched size >= n on this (path, axis)
        with self._lock:
            seen = self._buckets.get((path, axis))
            bigger = [s for s in seen if s >= n] if seen else []
        return min(bigger) if bigger else n

    def _train_target(self, path: str, n: int) -> int:
        """Auto-mode batch target for a TRAINING dispatch: rent (pad onto
        the smallest compiled bucket) vs buy (compile n natively) by the
        ski-rental rule — see the module docstring."""
        with self._lock:
            hist = self._hist.setdefault((path, "batch"), OrderedDict())
            count = hist.pop(n, 0) + 1
            hist[n] = count
            while len(hist) > 4 * self.max_buckets:
                hist.popitem(last=False)
            od = self._buckets.get((path, "batch"))
            bigger = [s for s in od if s >= n] if od else []
        if not bigger:
            return n                       # first/largest shape: never pad
        target = min(bigger)
        if target == n:
            return n
        waste_frac = (target - n) / n      # padded_flops/real_flops - 1
        compile_s, step_s = self._costs()
        if count * step_s * waste_frac >= \
                self._recompile_bias * compile_s:
            # this size recurs enough that its cumulative padding waste
            # now rivals a fresh compile — stop renting, buy the bucket
            self._note_skip(path)
            return n
        return target

    # ------------------------------------------------- checkpoint support
    def snapshot(self) -> Dict:
        """JSON-serializable view of the dispatched-size history AND the
        requested-size recurrence counts (``faulttolerance`` checkpoints
        carry it so a resumed run makes the same padding decisions — and
        hits the same compiled shapes — as the uninterrupted one)."""
        with self._lock:
            return {"mode": self.mode,
                    "batch_buckets": self.batch_buckets,
                    "time_buckets": self.time_buckets,
                    "cap": self.max_buckets,
                    "seen": [[path, axis, list(sizes)]
                             for (path, axis), sizes
                             in sorted(self._buckets.items())],
                    "hist": [[path, axis, [[s, c] for s, c in hist.items()]]
                             for (path, axis), hist
                             in sorted(self._hist.items())]}

    def restore_state(self, snap: Dict) -> None:
        """Merge a :meth:`snapshot` back in (mode/ladders stay as
        configured — bucket history, recurrence counts and the LRU cap are
        resume state).  Accepts pre-cost-model snapshots (no ``hist``/
        ``cap`` keys)."""
        cap = snap.get("cap")
        if cap:
            self.max_buckets = int(cap)
        with self._lock:
            for path, axis, sizes in snap.get("seen", []):
                od = self._buckets.setdefault((str(path), str(axis)),
                                              OrderedDict())
                for s in sizes:            # snapshot order = LRU order
                    od.pop(int(s), None)
                    od[int(s)] = None
                while len(od) > self.max_buckets:
                    od.popitem(last=False)
            for path, axis, pairs in snap.get("hist", []):
                hist = self._hist.setdefault((str(path), str(axis)),
                                             OrderedDict())
                for s, c in pairs:
                    hist[int(s)] = hist.pop(int(s), 0) + int(c)
                while len(hist) > 4 * self.max_buckets:
                    hist.popitem(last=False)

    def observe(self, path: str, n: int, axis: str = "batch") -> None:
        """Record a dispatched size so later smaller batches pad up to it
        (``auto`` mode); other modes derive targets from the ladder."""
        if n <= 0:
            return
        with self._lock:
            self._note_dispatch(path, axis, int(n))

    def target_batch(self, path: str, n: int) -> int:
        if self.mode == "auto":
            t = self._train_target(path, n)
        else:
            t = self._target(path, "batch", n)
        self.observe(path, t)
        return t

    def target_time(self, path: str, t: int) -> int:
        # time-axis padding needs masks the auto mode must not invent for
        # models that never used them — explicit modes only
        if self.mode not in ("pow2", "buckets"):
            return t
        tt = self._target(path, "time", t)
        self.observe(path, tt, axis="time")
        return tt

    # ------------------------------------------------------------ padding
    def pad_train_batch(self, x, y, mask, label_mask, path: str = "train"):
        """Pad a training batch to its bucket; returns (x, y, mask,
        label_mask) with padded rows/timesteps loss-masked.  Passes the
        batch through untouched when no padding applies or when padding
        cannot be expressed safely (feature mask present but no label mask
        — the step would fall back to the propagated mask, which padding
        must not override)."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, y, mask, label_mask
        if mask is not None and label_mask is None:
            return x, y, mask, label_mask
        target_b = self.target_batch(path, n)
        ndim = getattr(x, "ndim", 2)
        t = int(x.shape[1]) if ndim == 3 else 0
        target_t = self.target_time(path, t) if t else 0
        pad_b, pad_t = target_b - n, (target_t - t if t else 0)
        self._note_ratio(path, (target_b / n) *
                         (target_t / t if t and target_t > t else 1.0))
        if pad_b <= 0 and pad_t <= 0:
            return x, y, mask, label_mask
        import jax.numpy as jnp
        y_seq = getattr(y, "ndim", 2) == 3
        if label_mask is None:
            label_mask = jnp.ones((n, t) if y_seq and t else (n,),
                                  jnp.float32)
        if pad_t > 0:
            # padded timesteps: zeros in data and in BOTH masks (the
            # standard variable-length convention layers already honor)
            if mask is None:
                mask = jnp.ones((n, t), jnp.float32)
            x = _pad_time(x, pad_t)
            mask = _pad_time(mask, pad_t)
            if y_seq:
                y = _pad_time(y, pad_t)
            if getattr(label_mask, "ndim", 1) == 2:
                label_mask = _pad_time(label_mask, pad_t)
        if pad_b > 0:
            # padded rows: edge-repeat data/feature-mask (well-conditioned
            # forward), zero label mask (no loss/gradient contribution)
            x = _pad_rows(x, pad_b)
            y = _pad_rows(y, pad_b)
            if mask is not None:
                mask = _pad_rows(mask, pad_b)
            label_mask = _pad_rows(label_mask, pad_b, zero=True)
        return x, y, mask, label_mask

    # recurring (per-call) eval paths bound their padding waste: in auto
    # mode a target more than 8x the real batch (and more than 8 rows of
    # slack) is skipped — compiling the small shape once beats paying the
    # big bucket's compute on every call (output(1) after a 512-batch
    # validation pass must not run a 512-row forward forever).  One-off
    # training tails stay uncapped (a compile always dwarfs one step), and
    # explicit ladders are respected as configured.
    _EVAL_PAD_RATIO_CAP = 8

    def _eval_target(self, path: str, n: int) -> int:
        target = self._target(path, "batch", n)
        if self.mode == "auto" and target > n and \
                target > self._EVAL_PAD_RATIO_CAP * n and target - n > 8:
            target = n
            self._note_skip(path)
        self.observe(path, target)
        if n > 0:
            self._note_ratio(path, target / n)
        return target

    def pad_eval_rows(self, x, path: str = "eval"):
        """Pad an inference/eval batch's rows to the bucket.  Returns
        (padded_x, real_n); the caller slices outputs back to ``real_n``.
        Row-wise inference programs make this value-preserving."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, n
        target = self._eval_target(path, n)
        if target <= n:
            return x, n
        return _pad_rows(x, target - n), n

    def pad_eval_rows_multi(self, xs, path: str = "eval"):
        """Multi-input variant (ComputationGraph): one shared target for
        every input.  Returns (padded_xs, real_n)."""
        if not xs:
            return xs, -1
        n = int(getattr(xs[0], "shape", (0,))[0])
        if n == 0:
            return xs, n
        target = self._eval_target(path, n)
        if target <= n:
            return xs, n
        return [_pad_rows(x, target - n) for x in xs], n

    @staticmethod
    def _ones_label_mask(n: int, y):
        """All-ones label mask shaped for ``y``: (n, t) for sequence
        labels, (n,) otherwise."""
        import jax.numpy as jnp
        if getattr(y, "ndim", 2) == 3:
            return jnp.ones((n, int(y.shape[1])), jnp.float32)
        return jnp.ones((n,), jnp.float32)

    def pad_score_batch(self, x, y, label_mask=None, path: str = "score"):
        """Pad a scoring batch; returns (x, y, label_mask) where
        label_mask is None exactly when nothing was padded (keeps the
        steady score trace identical to the pre-policy one)."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, y, label_mask
        target = self._eval_target(path, n)
        if target <= n:
            return x, y, label_mask
        pad = target - n
        if label_mask is None:
            label_mask = self._ones_label_mask(n, y)
        return (_pad_rows(x, pad), _pad_rows(y, pad),
                _pad_rows(label_mask, pad, zero=True))

    def pad_multi_batch(self, xs, ys, lms, path: str = "train"):
        """Multi-input/multi-output row padding (ComputationGraph fit and
        score): one shared target across inputs; every output head gets a
        zero-extended label mask.  ``lms`` stays None when nothing pads."""
        if not xs:
            return xs, ys, lms
        n = int(getattr(xs[0], "shape", (0,))[0])
        if n == 0:
            return xs, ys, lms
        if path == "train":
            target = self.target_batch(path, n)
            self._note_ratio(path, target / n)
        else:
            target = self._eval_target(path, n)
        if target <= n:
            return xs, ys, lms
        pad = target - n
        xs = [_pad_rows(x, pad) for x in xs]
        new_lms = []
        for oi, y in enumerate(ys):
            lm = None if lms is None else lms[oi]
            if lm is None:
                lm = self._ones_label_mask(n, y)
            new_lms.append(_pad_rows(lm, pad, zero=True))
        ys = [_pad_rows(y, pad) for y in ys]
        return xs, ys, new_lms


def default_shape_policy(env: Optional[Dict[str, str]] = None) -> ShapePolicy:
    """Policy from ``DL4J_TPU_SHAPE_BUCKETS``: ``off``, ``pow2``, a
    comma-separated bucket ladder (``"8,16,64"``), or unset → ``auto``."""
    raw = (env if env is not None else os.environ).get(
        "DL4J_TPU_SHAPE_BUCKETS", "").strip().lower()
    if not raw or raw == "auto":
        return ShapePolicy("auto")
    if raw in ("off", "0", "none", "disabled"):
        return ShapePolicy("off")
    if raw == "pow2":
        return ShapePolicy("pow2")
    try:
        buckets = [int(v) for v in raw.split(",") if v.strip()]
    except ValueError:
        raise ValueError(
            f"DL4J_TPU_SHAPE_BUCKETS={raw!r}: expected 'off', 'pow2', "
            "'auto', or a comma-separated ladder like '8,16,64'")
    return ShapePolicy("buckets", batch_buckets=buckets)
