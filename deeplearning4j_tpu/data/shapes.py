"""Static-shape batch bucketing: pad ragged batches onto compiled shapes.

XLA compiles one executable per input shape, so a partial final batch or a
novel RNN sequence length retraces the whole train step.  ``ShapePolicy``
pads such batches up to a *bucket* — a shape the process has already
compiled (``auto`` mode) or a fixed ladder (explicit buckets / powers of
two, mirroring ``ParallelInference``'s inference-side buckets) — and masks
the padded rows out of the loss through the train step's existing
``label_mask`` argument, so the padded step is numerically identical to the
unpadded one (loss denominators count only rows whose mask has any weight;
see ``nn/losses._apply_mask_and_mean``).

Padded ROWS repeat the batch's last real row (keeps every forward op
well-conditioned: no zero-mask divisions, no degenerate statistics) and
carry a zero label mask; padded TIMESTEPS (explicit-bucket/pow2 modes only)
are zero-masked in both the feature and label masks, the same convention
variable-length sequence batches already use.

Known caveats (the networks gate on these — ``_pad_flags``): padding is
skipped entirely for AUX_LOSS stacks (MoE: padded rows compete for expert
capacity even at inference, and the whole-batch load-balancing term
defeats the label mask), for loss paths whose head ignores masks (YOLO),
and for training when the stack contains a cross-batch layer
(BatchNormalization trains on batch statistics, which padded rows would
perturb — eval uses running statistics and stays safe).  Recurring eval
paths additionally cap padding waste at 8x the real batch (auto mode).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence, Set, Tuple

__all__ = ["ShapePolicy", "default_shape_policy", "next_pow2"]


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _pad_rows(a, pad: int, zero: bool = False):
    """Append ``pad`` rows: copies of the last real row, or zeros."""
    import jax.numpy as jnp
    a = jnp.asarray(a)
    tail = jnp.zeros_like(a[-1:]) if zero else a[-1:]
    return jnp.concatenate([a] + [tail] * pad, axis=0)


def _pad_time(a, pad: int):
    """Append ``pad`` zero timesteps on axis 1."""
    import jax.numpy as jnp
    a = jnp.asarray(a)
    shape = list(a.shape)
    shape[1] = pad
    return jnp.concatenate([a, jnp.zeros(shape, a.dtype)], axis=1)


class ShapePolicy:
    """Pad-to-bucket policy for one network.

    Modes:
      - ``auto`` (default): pad a batch up to the smallest batch size this
        policy has already dispatched on the same path — the ragged *final*
        batch of an epoch rides the steady batch's compiled executable.
        Never pads the first/largest shape, so uniform workloads are
        untouched.  Batch axis only.
      - ``pow2``: pad the batch axis to the next power of two; 3-D inputs
        also pad the time axis to the next power of two.
      - ``buckets``: explicit ladders (``batch_buckets`` required,
        ``time_buckets`` optional); a size beyond the top bucket passes
        through unpadded (one compile, same as today).
      - ``off``: disabled.

    Thread-safe: the training masters drive replicas from worker threads.
    """

    def __init__(self, mode: str = "auto",
                 batch_buckets: Optional[Sequence[int]] = None,
                 time_buckets: Optional[Sequence[int]] = None):
        if mode not in ("auto", "pow2", "buckets", "off"):
            raise ValueError(f"unknown shape-policy mode '{mode}'")
        if mode == "buckets" and not batch_buckets:
            raise ValueError("mode='buckets' needs batch_buckets")
        self.mode = mode
        self.batch_buckets = sorted(int(b) for b in batch_buckets) \
            if batch_buckets else None
        self.time_buckets = sorted(int(b) for b in time_buckets) \
            if time_buckets else None
        self._seen: Dict[Tuple[str, str], Set[int]] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # ------------------------------------------------------------ targets
    def _target(self, path: str, axis: str, n: int) -> int:
        if self.mode == "off" or n <= 0:
            return n
        if self.mode == "buckets":
            ladder = self.batch_buckets if axis == "batch" \
                else self.time_buckets
            if not ladder:
                return n
            for b in ladder:
                if n <= b:
                    return b
            return n  # beyond top bucket: dispatch unpadded
        if self.mode == "pow2":
            return next_pow2(n)
        # auto: smallest already-dispatched size >= n on this (path, axis)
        with self._lock:
            seen = self._seen.get((path, axis))
            bigger = [s for s in seen if s >= n] if seen else []
        return min(bigger) if bigger else n

    # ------------------------------------------------- checkpoint support
    def snapshot(self) -> Dict:
        """JSON-serializable view of the dispatched-size history
        (``faulttolerance`` checkpoints carry it so a resumed run makes
        the same padding decisions — and hits the same compiled shapes —
        as the uninterrupted one)."""
        with self._lock:
            return {"mode": self.mode,
                    "batch_buckets": self.batch_buckets,
                    "time_buckets": self.time_buckets,
                    "seen": [[path, axis, sorted(sizes)]
                             for (path, axis), sizes
                             in sorted(self._seen.items())]}

    def restore_state(self, snap: Dict) -> None:
        """Merge a :meth:`snapshot`'s dispatched-size history back in
        (mode/ladders stay as configured — only the auto-mode bucket
        history is resume state)."""
        with self._lock:
            for path, axis, sizes in snap.get("seen", []):
                self._seen.setdefault((str(path), str(axis)), set()).update(
                    int(s) for s in sizes)

    def observe(self, path: str, n: int, axis: str = "batch") -> None:
        """Record a dispatched size so later smaller batches pad up to it
        (``auto`` mode); other modes derive targets from the ladder."""
        if n <= 0:
            return
        with self._lock:
            self._seen.setdefault((path, axis), set()).add(int(n))

    def target_batch(self, path: str, n: int) -> int:
        t = self._target(path, "batch", n)
        self.observe(path, t)
        return t

    def target_time(self, path: str, t: int) -> int:
        # time-axis padding needs masks the auto mode must not invent for
        # models that never used them — explicit modes only
        if self.mode not in ("pow2", "buckets"):
            return t
        tt = self._target(path, "time", t)
        self.observe(path, tt, axis="time")
        return tt

    # ------------------------------------------------------------ padding
    def pad_train_batch(self, x, y, mask, label_mask, path: str = "train"):
        """Pad a training batch to its bucket; returns (x, y, mask,
        label_mask) with padded rows/timesteps loss-masked.  Passes the
        batch through untouched when no padding applies or when padding
        cannot be expressed safely (feature mask present but no label mask
        — the step would fall back to the propagated mask, which padding
        must not override)."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, y, mask, label_mask
        if mask is not None and label_mask is None:
            return x, y, mask, label_mask
        target_b = self.target_batch(path, n)
        ndim = getattr(x, "ndim", 2)
        t = int(x.shape[1]) if ndim == 3 else 0
        target_t = self.target_time(path, t) if t else 0
        pad_b, pad_t = target_b - n, (target_t - t if t else 0)
        if pad_b <= 0 and pad_t <= 0:
            return x, y, mask, label_mask
        import jax.numpy as jnp
        y_seq = getattr(y, "ndim", 2) == 3
        if label_mask is None:
            label_mask = jnp.ones((n, t) if y_seq and t else (n,),
                                  jnp.float32)
        if pad_t > 0:
            # padded timesteps: zeros in data and in BOTH masks (the
            # standard variable-length convention layers already honor)
            if mask is None:
                mask = jnp.ones((n, t), jnp.float32)
            x = _pad_time(x, pad_t)
            mask = _pad_time(mask, pad_t)
            if y_seq:
                y = _pad_time(y, pad_t)
            if getattr(label_mask, "ndim", 1) == 2:
                label_mask = _pad_time(label_mask, pad_t)
        if pad_b > 0:
            # padded rows: edge-repeat data/feature-mask (well-conditioned
            # forward), zero label mask (no loss/gradient contribution)
            x = _pad_rows(x, pad_b)
            y = _pad_rows(y, pad_b)
            if mask is not None:
                mask = _pad_rows(mask, pad_b)
            label_mask = _pad_rows(label_mask, pad_b, zero=True)
        return x, y, mask, label_mask

    # recurring (per-call) eval paths bound their padding waste: in auto
    # mode a target more than 8x the real batch (and more than 8 rows of
    # slack) is skipped — compiling the small shape once beats paying the
    # big bucket's compute on every call (output(1) after a 512-batch
    # validation pass must not run a 512-row forward forever).  One-off
    # training tails stay uncapped (a compile always dwarfs one step), and
    # explicit ladders are respected as configured.
    _EVAL_PAD_RATIO_CAP = 8

    def _eval_target(self, path: str, n: int) -> int:
        target = self._target(path, "batch", n)
        if self.mode == "auto" and target > n and \
                target > self._EVAL_PAD_RATIO_CAP * n and target - n > 8:
            target = n
        self.observe(path, target)
        return target

    def pad_eval_rows(self, x, path: str = "eval"):
        """Pad an inference/eval batch's rows to the bucket.  Returns
        (padded_x, real_n); the caller slices outputs back to ``real_n``.
        Row-wise inference programs make this value-preserving."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, n
        target = self._eval_target(path, n)
        if target <= n:
            return x, n
        return _pad_rows(x, target - n), n

    def pad_eval_rows_multi(self, xs, path: str = "eval"):
        """Multi-input variant (ComputationGraph): one shared target for
        every input.  Returns (padded_xs, real_n)."""
        if not xs:
            return xs, -1
        n = int(getattr(xs[0], "shape", (0,))[0])
        if n == 0:
            return xs, n
        target = self._eval_target(path, n)
        if target <= n:
            return xs, n
        return [_pad_rows(x, target - n) for x in xs], n

    @staticmethod
    def _ones_label_mask(n: int, y):
        """All-ones label mask shaped for ``y``: (n, t) for sequence
        labels, (n,) otherwise."""
        import jax.numpy as jnp
        if getattr(y, "ndim", 2) == 3:
            return jnp.ones((n, int(y.shape[1])), jnp.float32)
        return jnp.ones((n,), jnp.float32)

    def pad_score_batch(self, x, y, label_mask=None, path: str = "score"):
        """Pad a scoring batch; returns (x, y, label_mask) where
        label_mask is None exactly when nothing was padded (keeps the
        steady score trace identical to the pre-policy one)."""
        n = int(getattr(x, "shape", (0,))[0])
        if n == 0:
            return x, y, label_mask
        target = self._eval_target(path, n)
        if target <= n:
            return x, y, label_mask
        pad = target - n
        if label_mask is None:
            label_mask = self._ones_label_mask(n, y)
        return (_pad_rows(x, pad), _pad_rows(y, pad),
                _pad_rows(label_mask, pad, zero=True))

    def pad_multi_batch(self, xs, ys, lms, path: str = "train"):
        """Multi-input/multi-output row padding (ComputationGraph fit and
        score): one shared target across inputs; every output head gets a
        zero-extended label mask.  ``lms`` stays None when nothing pads."""
        if not xs:
            return xs, ys, lms
        n = int(getattr(xs[0], "shape", (0,))[0])
        if n == 0:
            return xs, ys, lms
        target = self.target_batch(path, n) if path == "train" \
            else self._eval_target(path, n)
        if target <= n:
            return xs, ys, lms
        pad = target - n
        xs = [_pad_rows(x, pad) for x in xs]
        new_lms = []
        for oi, y in enumerate(ys):
            lm = None if lms is None else lms[oi]
            if lm is None:
                lm = self._ones_label_mask(n, y)
            new_lms.append(_pad_rows(lm, pad, zero=True))
        ys = [_pad_rows(y, pad) for y in ys]
        return xs, ys, new_lms


def default_shape_policy(env: Optional[Dict[str, str]] = None) -> ShapePolicy:
    """Policy from ``DL4J_TPU_SHAPE_BUCKETS``: ``off``, ``pow2``, a
    comma-separated bucket ladder (``"8,16,64"``), or unset → ``auto``."""
    raw = (env if env is not None else os.environ).get(
        "DL4J_TPU_SHAPE_BUCKETS", "").strip().lower()
    if not raw or raw == "auto":
        return ShapePolicy("auto")
    if raw in ("off", "0", "none", "disabled"):
        return ShapePolicy("off")
    if raw == "pow2":
        return ShapePolicy("pow2")
    try:
        buckets = [int(v) for v in raw.split(",") if v.strip()]
    except ValueError:
        raise ValueError(
            f"DL4J_TPU_SHAPE_BUCKETS={raw!r}: expected 'off', 'pow2', "
            "'auto', or a comma-separated ladder like '8,16,64'")
    return ShapePolicy("buckets", batch_buckets=buckets)
