"""t-SNE (reference ``deeplearning4j-core/.../plot/BarnesHutTsne.java:65`` and
``plot/Tsne.java``).

TPU-first: the default ``method="exact"`` path computes the full [N,N]
affinity and repulsive-force matrices as fused matmuls under one ``jit`` —
O(N^2) FLOPs but MXU-resident, which on TPU beats pointer-chasing Barnes-Hut
for the N (≤ ~50k) t-SNE is used at.  ``method="barnes_hut"`` provides the
reference's O(N log N) algorithm (SPTree, theta-approximation) on host for
CPU parity.  Perplexity calibration is a vectorized jitted bisection (the
reference does per-row host bisection, ``Tsne.java`` ``computeGaussianPerplexity``).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .neighbors import BruteForceNN
from .sptree import SPTree

__all__ = ["BarnesHutTsne", "Tsne"]


@functools.partial(jax.jit, static_argnames=("iters",))  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _calibrate_p(d2, perplexity, iters: int = 50):
    """Row-wise bisection for Gaussian kernel precisions (beta = 1/2sigma^2)
    so each row's entropy == log(perplexity).  d2: [N,N] squared distances
    with +inf on the diagonal."""
    target = jnp.log(perplexity)
    n = d2.shape[0]

    def entropy_p(beta):
        logits = -d2 * beta[:, None]
        p = jax.nn.softmax(logits, axis=1)
        h = -jnp.sum(p * jnp.where(p > 1e-12, jnp.log(p), 0.0), axis=1)
        return h, p

    def body(state, _):
        beta, lo, hi = state
        h, _ = entropy_p(beta)
        too_high = h > target          # entropy too high -> increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0, (lo + hi) / 2.0)
        return (beta, lo, hi), None

    init = (jnp.ones(n), jnp.zeros(n), jnp.full(n, jnp.inf))
    (beta, _, _), _ = jax.lax.scan(body, init, None, length=iters)
    _, p = entropy_p(beta)
    return p


@jax.jit  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _tsne_grad_exact(y, p_sym):
    """Exact t-SNE gradient: attractive + repulsive via full Student-t kernel."""
    n = y.shape[0]
    y2 = jnp.sum(y * y, axis=1)
    d2 = y2[:, None] - 2.0 * (y @ y.T) + y2[None, :]
    num = 1.0 / (1.0 + d2)
    num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    q = num / jnp.maximum(num.sum(), 1e-12)
    pq = (p_sym - jnp.maximum(q, 1e-12)) * num          # [N,N]
    grad = 4.0 * (jnp.diag(pq.sum(1)) - pq) @ y         # MXU matmul
    kl = jnp.sum(p_sym * jnp.log(jnp.maximum(p_sym, 1e-12)
                                 / jnp.maximum(q, 1e-12)))
    return grad, kl


@jax.jit  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _gd_update(y, grad, vel, gains, lr, momentum):
    """Delta-bar-delta gains + momentum step (reference ``Tsne.java`` update).
    Gains are capped: with Student-t attraction, an overshoot past the kernel
    tail is unrecoverable (gradient vanishes), so unbounded gains diverge."""
    same_sign = (grad > 0) == (vel > 0)
    gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                     0.01, 10.0)
    vel = momentum * vel - lr * gains * grad
    y = y + vel
    return y - y.mean(0), vel, gains


class Tsne:
    """Exact t-SNE, fully jitted per iteration (the TPU path)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 max_iter: int = 1000, learning_rate: Optional[float] = None,
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 250,
                 stop_lying_iteration: int = 250, exaggeration: float = 12.0,
                 seed: int = 42):
        self.n_components = n_components
        self.perplexity = perplexity
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.seed = seed
        self.embedding: Optional[np.ndarray] = None
        self.kl_divergence: Optional[float] = None

    def _input_probabilities(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[0]
        x2 = jnp.sum(x * x, axis=1)
        d2 = x2[:, None] - 2.0 * (x @ x.T) + x2[None, :]
        d2 = d2.at[jnp.arange(n), jnp.arange(n)].set(jnp.inf)
        p = _calibrate_p(d2, jnp.asarray(self.perplexity, x.dtype))
        p_sym = (p + p.T) / (2.0 * n)
        return jnp.maximum(p_sym, 1e-12)

    def _lr(self, n: int) -> float:
        """Auto learning rate: N / exaggeration / 4, floored (sklearn-style
        heuristic, scaled down — small-N embeddings overshoot the Student-t
        attraction basin at the classic lr=200)."""
        if self.learning_rate is not None:
            return self.learning_rate
        return max(n / self.exaggeration / 4.0, 5.0)

    def fit(self, x) -> np.ndarray:
        x = jnp.asarray(np.asarray(x, dtype=np.float32))
        n = x.shape[0]
        lr = self._lr(n)
        p_sym = self._input_probabilities(x)
        key = jax.random.PRNGKey(self.seed)
        y = 1e-4 * jax.random.normal(key, (n, self.n_components), x.dtype)
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        kl = None
        for it in range(self.max_iter):
            lying = it < self.stop_lying_iteration
            p_eff = p_sym * self.exaggeration if lying else p_sym
            momentum = (self.initial_momentum
                        if it < self.switch_momentum_iteration
                        else self.final_momentum)
            grad, kl = _tsne_grad_exact(y, p_eff)
            y, vel, gains = _gd_update(y, grad, vel, gains, lr, momentum)
        self.embedding = np.asarray(y)
        self.kl_divergence = float(kl) if kl is not None else None
        return self.embedding


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (reference ``plot/BarnesHutTsne.java:65``): sparse
    kNN input affinities + SPTree theta-approximated repulsion, on host.

    ``theta=0`` falls back to the exact jitted path (same convention as the
    reference, ``BarnesHutTsne.java`` theta field).
    """

    def __init__(self, theta: float = 0.5, n_components: int = 2,
                 perplexity: float = 30.0, max_iter: int = 1000,
                 learning_rate: Optional[float] = None, seed: int = 42, **kw):
        super().__init__(n_components=n_components, perplexity=perplexity,
                         max_iter=max_iter, learning_rate=learning_rate,
                         seed=seed, **kw)
        self.theta = theta

    def fit(self, x) -> np.ndarray:
        if self.theta <= 0.0:
            return super().fit(x)
        x_np = np.asarray(x, dtype=np.float32)
        n = len(x_np)
        k = min(int(3 * self.perplexity), n - 1)
        # kNN on device (distance matmul), calibration on the sparse rows
        dist, idx = BruteForceNN(x_np).query(x_np, k + 1)
        dist, idx = dist[:, 1:], idx[:, 1:]                 # drop self
        d2 = jnp.asarray(dist.astype(np.float64)) ** 2
        p_cond = np.asarray(_calibrate_p(
            d2, jnp.asarray(min(self.perplexity, k / 3.0))))
        # symmetrize the sparse matrix: P = (P + P^T) / 2N as dense-of-sparse
        rows = np.repeat(np.arange(n), k)
        p_dense = np.zeros((n, n))
        p_dense[rows, idx.ravel()] = p_cond.ravel()
        p_sym = (p_dense + p_dense.T) / (2.0 * n)
        rng = np.random.default_rng(self.seed)
        lr = self._lr(n)
        y = 1e-4 * rng.standard_normal((n, self.n_components))
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        kl = None
        nz = p_sym.nonzero()
        p_vals = p_sym[nz]
        for it in range(self.max_iter):
            lying = it < self.stop_lying_iteration
            p_eff = p_vals * (self.exaggeration if lying else 1.0)
            momentum = (self.initial_momentum
                        if it < self.switch_momentum_iteration
                        else self.final_momentum)
            # attractive forces over the sparse edges
            diff = y[nz[0]] - y[nz[1]]
            w = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
            attr = np.zeros_like(y)
            np.add.at(attr, nz[0], (p_eff * w)[:, None] * diff)
            # repulsive via SPTree
            tree = SPTree(y)
            neg = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                f, zi = tree.compute_non_edge_forces(i, self.theta)
                neg[i] = f
                z += zi
            grad = 4.0 * (attr - neg / max(z, 1e-12))
            same = (grad > 0) == (vel > 0)
            gains = np.clip(np.where(same, gains * 0.8, gains + 0.2), 0.01, 10.0)
            vel = momentum * vel - lr * gains * grad
            y = y + vel
            y = y - y.mean(0)
        q_un = w  # reuse last attractive kernel for a cheap KL estimate
        kl = float(np.sum(p_vals * np.log(np.maximum(p_vals, 1e-12)
                                          / np.maximum(q_un / max(z, 1e-12), 1e-12))))
        self.embedding = y
        self.kl_divergence = kl
        return y
