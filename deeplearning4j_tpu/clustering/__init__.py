"""Clustering & nearest neighbors (reference
``deeplearning4j-nearestneighbors-parent/nearestneighbor-core`` +
``deeplearning4j-core/.../plot/``): KMeans, VPTree/KDTree/brute-force kNN,
SPTree, and t-SNE (exact jitted + Barnes-Hut)."""
from .kmeans import ClusterSet, KMeans
from .neighbors import BruteForceNN, KDTree, VPTree, pairwise_distance
from .sptree import SPTree
from .tsne import BarnesHutTsne, Tsne
from .algorithm import (BaseClusteringAlgorithm, ClusteringOptimizationType,
                        ClusterSetInfo, ConvergenceCondition,
                        FixedClusterCountStrategy,
                        FixedIterationCountCondition, IterationHistory,
                        IterationInfo, KMeansClustering, OptimisationStrategy,
                        VarianceVariationCondition)

__all__ = ["KMeans", "ClusterSet", "BruteForceNN", "VPTree", "KDTree",
           "pairwise_distance", "SPTree", "Tsne", "BarnesHutTsne",
           "BaseClusteringAlgorithm", "ClusteringOptimizationType",
           "ClusterSetInfo", "ConvergenceCondition",
           "FixedClusterCountStrategy", "FixedIterationCountCondition",
           "IterationHistory", "IterationInfo", "KMeansClustering",
           "OptimisationStrategy", "VarianceVariationCondition"]
