"""KMeans clustering (reference ``clustering/kmeans/KMeansClustering.java`` +
the generic strategy machinery in ``clustering/algorithm/BaseClusteringAlgorithm.java``).

TPU-first: one Lloyd iteration is a distance Gram matrix (MXU matmul), an
argmin, and a segment-sum — all fused under one ``jit``; the convergence
check (distribution-variation threshold, reference
``clustering/strategy/FixedClusterCountStrategy`` / ``ConvergenceCondition``)
runs on host between jitted steps.  Empty clusters are re-seeded from the
point farthest from its centroid (reference handles this by cluster-splitting
in ``ClusterUtils.refreshClustersCenters``).
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .neighbors import pairwise_distance

__all__ = ["KMeans", "ClusterSet", "kmeanspp_init"]


def _assign_refresh(points, centers, metric: str):
    """Shared Lloyd core (used by KMeans and the strategy framework in
    ``algorithm.py``): distance Gram matrix, argmin assignment, one-hot,
    counts, refreshed centers (old center kept where a cluster went empty)."""
    d = pairwise_distance(points, centers, metric)          # [N,K]
    assign = jnp.argmin(d, axis=1)                          # [N]
    one_hot = jax.nn.one_hot(assign, centers.shape[0],
                             dtype=points.dtype)            # [N,K]
    counts = one_hot.sum(axis=0)                            # [K]
    sums = one_hot.T @ points                               # [K,D]  (MXU)
    new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
    new_centers = jnp.where((counts > 0)[:, None], new_centers, centers)
    return d, assign, one_hot, counts, new_centers


@functools.partial(jax.jit, static_argnames=("metric",))  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _lloyd_step(points, centers, metric: str):
    d, assign, _, counts, new_centers = _assign_refresh(points, centers, metric)
    cost = jnp.sum(jnp.min(d, axis=1))
    # farthest point from its own centroid (used for empty-cluster reseed)
    far = jnp.argmax(jnp.min(d, axis=1))
    return new_centers, assign, counts, cost, far


def kmeanspp_init(points: np.ndarray, k: int, rng,
                  metric: str = "euclidean") -> np.ndarray:
    """Distance-weighted (k-means++) seeding: each next center is sampled with
    probability proportional to its squared distance from the nearest chosen
    center (the reference's initClusters loop,
    ``clustering/algorithm/BaseClusteringAlgorithm.java:145-160``)."""
    n = len(points)
    if k > n:
        raise ValueError(
            f"k={k} clusters requested but only {n} points given")
    centers = [points[rng.integers(n)]]
    d2 = None
    for _ in range(1, k):
        cur = np.asarray(pairwise_distance(
            jnp.asarray(points), jnp.asarray(np.stack(centers[-1:])),
            metric))[:, 0] ** 2
        d2 = cur if d2 is None else np.minimum(d2, cur)
        total = d2.sum()
        if total <= 0.0:  # all points coincide with a center: uniform pick
            centers.append(points[rng.integers(n)])
            continue
        centers.append(points[rng.choice(n, p=d2 / total)])
    return np.stack(centers)


@dataclass
class ClusterSet:
    """Result of clustering: centers + assignment (reference ``ClusterSet``)."""
    centers: np.ndarray
    assignments: np.ndarray
    cost: float
    iterations: int

    def nearest_cluster(self, points, metric: str = "euclidean") -> np.ndarray:
        d = pairwise_distance(jnp.atleast_2d(jnp.asarray(points)),
                              jnp.asarray(self.centers), metric)
        return np.asarray(jnp.argmin(d, axis=1))


class KMeans:
    """Lloyd's algorithm with k-means++ init and empty-cluster reseeding."""

    def __init__(self, k: int, max_iterations: int = 100,
                 metric: str = "euclidean", tol: float = 1e-4,
                 seed: int = 0, init: str = "kmeans++"):
        self.k = k
        self.max_iterations = max_iterations
        self.metric = metric
        self.tol = tol
        self.seed = seed
        self.init = init

    def _init_centers(self, points: np.ndarray, rng) -> np.ndarray:
        if self.init == "random":
            return points[rng.choice(len(points), self.k, replace=False)]
        return kmeanspp_init(points, self.k, rng, self.metric)

    def fit(self, points) -> ClusterSet:
        points_np = np.asarray(points, dtype=np.float32)
        rng = np.random.default_rng(self.seed)
        centers = jnp.asarray(self._init_centers(points_np, rng))
        pts = jnp.asarray(points_np)
        prev_cost = np.inf
        assign = counts = None
        it = 0
        for it in range(1, self.max_iterations + 1):
            centers, assign, counts, cost, far = _lloyd_step(pts, centers, self.metric)
            # deliberate per-iteration host syncs: tol-based convergence
            # and empty-cluster repair are host-side decisions — Lloyd's
            # loop cannot proceed without the values
            counts_np = np.asarray(counts)  # graftlint: disable=JX003
            if (counts_np == 0).any():
                centers_np = np.asarray(centers)  # graftlint: disable=JX003,JX012
                # graftlint: disable=JX003
                centers_np[np.flatnonzero(counts_np == 0)[0]] = points_np[int(far)]
                centers = jnp.asarray(centers_np)
                continue
            cost = float(cost)  # graftlint: disable=JX003
            if abs(prev_cost - cost) <= self.tol * max(abs(prev_cost), 1.0):
                prev_cost = cost
                break
            prev_cost = cost
        return ClusterSet(np.asarray(centers), np.asarray(assign),
                          prev_cost, it)
