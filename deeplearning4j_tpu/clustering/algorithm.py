"""Generic clustering framework: strategies, termination conditions, iteration
history and cluster splitting.

Reference: ``clustering/algorithm/BaseClusteringAlgorithm.java`` (iterate:
classify points -> refresh centers -> apply strategy until the termination
condition holds), ``clustering/strategy/`` (FixedClusterCountStrategy,
OptimisationStrategy), ``clustering/condition/`` (ConvergenceCondition,
FixedIterationCountCondition, VarianceVariationCondition),
``clustering/optimisation/ClusteringOptimizationType.java``,
``clustering/info/ClusterSetInfo.java``, ``clustering/iteration/``.

TPU-first: the reference classifies points with a thread pool
(``ClusterUtils.classifyPoints`` over an ExecutorService); here ONE jitted
program computes the full distance Gram matrix (MXU matmul for euclidean/
cosine), the argmin assignment, the refreshed centers and every per-cluster
statistic the strategies need (counts, mean/max point-to-center distance,
distance variance) via one-hot segment reductions.  Only the strategy
decisions (split/terminate) run on host between steps — they are O(K) and
data-dependent, which is exactly what should NOT live under ``jit``.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .neighbors import pairwise_distance
from .kmeans import ClusterSet, _assign_refresh, kmeanspp_init

__all__ = [
    "ClusteringOptimizationType", "ClusterSetInfo", "IterationInfo",
    "IterationHistory", "ConvergenceCondition", "FixedIterationCountCondition",
    "VarianceVariationCondition", "FixedClusterCountStrategy",
    "OptimisationStrategy", "BaseClusteringAlgorithm", "KMeansClustering",
]


class ClusteringOptimizationType(Enum):
    """``clustering/optimisation/ClusteringOptimizationType.java``."""
    MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE = "avg_to_center"
    MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE = "max_to_center"
    MINIMIZE_AVERAGE_POINT_TO_POINT_DISTANCE = "avg_to_point"
    MINIMIZE_MAXIMUM_POINT_TO_POINT_DISTANCE = "max_to_point"
    MINIMIZE_PER_CLUSTER_POINT_COUNT = "point_count"


@functools.partial(jax.jit, static_argnames=("metric",))  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _classify_and_refresh(points, centers, prev_assign, metric: str):
    """One full reference iteration (classifyPoints + refreshClustersCenters +
    computeClusterSetInfo) as a single fused program: the shared Lloyd core
    from ``kmeans.py`` plus the per-cluster statistics the strategies need."""
    d, assign, one_hot, counts, new_centers = \
        _assign_refresh(points, centers, metric)
    mind = jnp.min(d, axis=1)                                 # [N]
    safe = jnp.maximum(counts, 1.0)
    # per-cluster point-to-center stats (against the refreshed assignment)
    avg_d = (one_hot.T @ mind[:, None])[:, 0] / safe
    max_d = jnp.max(jnp.where(one_hot > 0, d, -jnp.inf), axis=0)
    max_d = jnp.where(counts > 0, max_d, 0.0)
    var_d = (one_hot.T @ (mind**2)[:, None])[:, 0] / safe - avg_d**2
    n_changed = jnp.sum(assign != prev_assign)
    # farthest member per cluster — the split point for spread-out clusters
    far_idx = jnp.argmax(jnp.where(one_hot > 0, d, -jnp.inf), axis=0)
    return (new_centers, assign, counts, avg_d, max_d, var_d,
            jnp.sum(mind), n_changed, far_idx)


@dataclass
class ClusterSetInfo:
    """Per-iteration cluster statistics (``clustering/info/ClusterSetInfo.java``:
    per-cluster averagePointDistanceFromCenter / maxPointDistanceFromCenter /
    pointDistanceFromCenterVariance, set-level pointLocationChange)."""
    counts: np.ndarray                 # [K] points per cluster
    avg_distance: np.ndarray           # [K] mean point-to-center distance
    max_distance: np.ndarray           # [K] max point-to-center distance
    distance_variance: np.ndarray      # [K] variance of point-to-center dist
    total_cost: float                  # sum of min distances
    point_location_change: int         # points that switched cluster

    @property
    def points_count(self) -> int:
        return int(self.counts.sum())

    def point_distance_from_cluster_variance(self) -> float:
        """Set-level variance used by VarianceVariationCondition."""
        w = self.counts / max(self.counts.sum(), 1)
        return float((w * self.distance_variance).sum())


@dataclass
class IterationInfo:
    """``clustering/iteration/IterationInfo.java``."""
    index: int
    cluster_set_info: ClusterSetInfo
    strategy_applied: bool = False


@dataclass
class IterationHistory:
    """``clustering/iteration/IterationHistory.java``."""
    iterations: Dict[int, IterationInfo] = field(default_factory=dict)

    @property
    def iteration_count(self) -> int:
        return len(self.iterations)

    def most_recent(self) -> Optional[IterationInfo]:
        if not self.iterations:
            return None
        return self.iterations[max(self.iterations)]

    def get(self, index: int) -> IterationInfo:
        return self.iterations[index]


class ConvergenceCondition:
    """Distribution-variation-rate threshold
    (``condition/ConvergenceCondition.java``: fraction of points that changed
    cluster < rate)."""

    def __init__(self, rate: float):
        self.rate = rate

    @classmethod
    def distribution_variation_rate_less_than(cls, rate: float):
        return cls(rate)

    def is_satisfied(self, history: IterationHistory) -> bool:
        if history.iteration_count <= 1:
            return False
        info = history.most_recent().cluster_set_info
        return (info.point_location_change / max(info.points_count, 1)) < self.rate


class FixedIterationCountCondition:
    """``condition/FixedIterationCountCondition.java``."""

    def __init__(self, count: int):
        self.count = count

    @classmethod
    def iteration_count_greater_than(cls, count: int):
        return cls(count)

    def is_satisfied(self, history: IterationHistory) -> bool:
        return history.iteration_count >= self.count


class VarianceVariationCondition:
    """Relative variance change below threshold for ``period`` consecutive
    iterations (``condition/VarianceVariationCondition.java``).

    Intentional deviation from the reference: the threshold applies to the
    ABSOLUTE relative change |(cur-prev)/prev|, whereas the reference's
    LessThan comparison is on the signed change — there any variance
    decrease satisfies the condition immediately.  The absolute form is the
    saner convergence test (a large improvement should not read as
    'converged')."""

    def __init__(self, variation: float, period: int):
        self.variation = variation
        self.period = period

    @classmethod
    def variance_variation_less_than(cls, variation: float, period: int):
        return cls(variation, period)

    def is_satisfied(self, history: IterationHistory) -> bool:
        if history.iteration_count <= self.period:
            return False
        idx = max(history.iterations)
        for i in range(self.period):
            cur = history.get(idx - i).cluster_set_info \
                .point_distance_from_cluster_variance()
            prev = history.get(idx - i - 1).cluster_set_info \
                .point_distance_from_cluster_variance()
            if prev == 0:
                continue
            if abs((cur - prev) / prev) >= self.variation:
                return False
        return True


class _BaseStrategy:
    """``strategy/BaseClusteringStrategy.java``: initial cluster count,
    distance function, empty-cluster policy, termination condition."""

    def __init__(self, initial_cluster_count: int, metric: str = "euclidean",
                 allow_empty_clusters: bool = False):
        self.initial_cluster_count = initial_cluster_count
        self.metric = metric
        self.allow_empty_clusters = allow_empty_clusters
        self.termination_condition = None

    def end_when_iteration_count_equals(self, count: int):
        self.termination_condition = \
            FixedIterationCountCondition.iteration_count_greater_than(count)
        return self

    def end_when_distribution_variation_rate_less_than(self, rate: float):
        self.termination_condition = \
            ConvergenceCondition.distribution_variation_rate_less_than(rate)
        return self


class FixedClusterCountStrategy(_BaseStrategy):
    """K stays fixed; empty clusters are replaced by splitting the most
    spread-out clusters (``strategy/FixedClusterCountStrategy.java``)."""

    @classmethod
    def setup(cls, cluster_count: int, metric: str = "euclidean",
              allow_empty_clusters: bool = False):
        return cls(cluster_count, metric, allow_empty_clusters)


class OptimisationStrategy(_BaseStrategy):
    """Iteratively split clusters violating an optimization target
    (``strategy/OptimisationStrategy.java`` + ``ClusteringOptimization``)."""

    def __init__(self, initial_cluster_count: int, metric: str = "euclidean"):
        super().__init__(initial_cluster_count, metric,
                         allow_empty_clusters=False)
        self.optimization_type: Optional[ClusteringOptimizationType] = None
        self.optimization_value: float = 0.0
        self.optimization_period: int = 1

    @classmethod
    def setup(cls, initial_cluster_count: int, metric: str = "euclidean"):
        return cls(initial_cluster_count, metric)

    def optimize(self, opt_type: ClusteringOptimizationType, value: float):
        self.optimization_type = opt_type
        self.optimization_value = value
        return self

    def optimize_when_iteration_count_multiple_of(self, period: int):
        self.optimization_period = max(1, period)
        return self


class BaseClusteringAlgorithm:
    """Strategy-driven clustering loop
    (``algorithm/BaseClusteringAlgorithm.java``: applyTo = resetState +
    initClusters (k-means++-style distance-weighted seeding, :145-160) +
    iterations; applyClusteringStrategy handles empty-cluster removal,
    splitMostSpreadOutClusters and optimization splits)."""

    def __init__(self, strategy: _BaseStrategy, seed: int = 0,
                 max_iterations: int = 100):
        self.strategy = strategy
        self.seed = seed
        self.max_iterations = max_iterations
        self.history = IterationHistory()

    @classmethod
    def setup(cls, strategy: _BaseStrategy, **kw):
        return cls(strategy, **kw)

    def apply_to(self, points) -> ClusterSet:
        pts_np = np.asarray(points, dtype=np.float32)
        rng = np.random.default_rng(self.seed)
        strat = self.strategy
        centers = kmeanspp_init(pts_np, strat.initial_cluster_count, rng,
                                strat.metric).astype(np.float32)
        pts = jnp.asarray(pts_np)
        prev_assign = jnp.full((len(pts_np),), -1, dtype=jnp.int32)
        self.history = IterationHistory()
        cond = strat.termination_condition
        it = 0
        while it < self.max_iterations:
            it += 1
            (c_new, assign, counts, avg_d, max_d, var_d, cost, n_changed,
             far_idx) = _classify_and_refresh(
                pts, jnp.asarray(centers), prev_assign, strat.metric)
            prev_assign = assign
            # np.array (copy): _apply_strategy writes into this buffer, and
            # np.asarray on a device array yields a read-only view
            centers = np.array(c_new)
            info = ClusterSetInfo(np.asarray(counts), np.asarray(avg_d),
                                  np.asarray(max_d), np.asarray(var_d),
                                  float(cost), int(n_changed))
            self.history.iterations[it] = IterationInfo(it, info)
            applied, centers = self._apply_strategy(pts_np, centers, info,
                                                    np.asarray(far_idx), rng)
            self.history.iterations[it].strategy_applied = applied
            if applied:
                continue
            if cond is not None and cond.is_satisfied(self.history):
                break
            if cond is None and int(n_changed) == 0:
                break
        # final classification against the final centers — a strategy split on
        # the last iteration must not leave assignments/cost referring to the
        # pre-split center set
        d = pairwise_distance(pts, jnp.asarray(centers), strat.metric)
        assign = jnp.argmin(d, axis=1)
        cost = jnp.sum(jnp.min(d, axis=1))
        return ClusterSet(np.asarray(centers), np.asarray(assign),
                          float(cost), it)

    # -- strategy application ------------------------------------------------
    def _apply_strategy(self, pts_np, centers, info: ClusterSetInfo,
                        far_idx, rng):
        """Returns (applied, centers)."""
        strat = self.strategy
        applied = False
        if not strat.allow_empty_clusters:
            empties = np.flatnonzero(info.counts == 0)
            if len(empties):
                # replace each empty center by splitting the most spread-out
                # non-empty clusters (ClusterUtils.splitMostSpreadOutClusters);
                # more empties than donor clusters -> distinct random points
                donors = np.flatnonzero(info.counts > 0)
                donors = donors[np.argsort(-info.avg_distance[donors])]
                for i, e in enumerate(empties):
                    if i < len(donors):
                        centers[e] = pts_np[int(far_idx[int(donors[i])])]
                    else:
                        centers[e] = pts_np[rng.integers(len(pts_np))]
                applied = True
        if isinstance(strat, OptimisationStrategy) and strat.optimization_type:
            if self.history.iteration_count % strat.optimization_period == 0:
                new = self._optimization_splits(pts_np, centers, info, far_idx)
                if new is not None:
                    centers = new
                    applied = True
        return applied, centers

    def _optimization_splits(self, pts_np, centers, info: ClusterSetInfo,
                             far_idx) -> Optional[np.ndarray]:
        """Split every cluster violating the optimization target, adding its
        farthest member as a new center (ClusterUtils.applyOptimization)."""
        strat: OptimisationStrategy = self.strategy  # type: ignore
        t, v = strat.optimization_type, strat.optimization_value
        if t is ClusteringOptimizationType.MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE:
            bad = info.avg_distance > v
        elif t is ClusteringOptimizationType.MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE:
            bad = info.max_distance > v
        elif t is ClusteringOptimizationType.MINIMIZE_AVERAGE_POINT_TO_POINT_DISTANCE:
            # mean pairwise distance ~ 2x mean-to-center for a symmetric cloud
            bad = 2.0 * info.avg_distance > v
        elif t is ClusteringOptimizationType.MINIMIZE_MAXIMUM_POINT_TO_POINT_DISTANCE:
            bad = 2.0 * info.max_distance > v
        else:  # MINIMIZE_PER_CLUSTER_POINT_COUNT
            bad = info.counts > v
        bad &= info.counts > 1
        if not bad.any():
            return None
        extra = [pts_np[int(far_idx[int(c)])] for c in np.flatnonzero(bad)]
        return np.concatenate([centers, np.stack(extra)], axis=0)


class KMeansClustering(BaseClusteringAlgorithm):
    """``clustering/kmeans/KMeansClustering.java`` setup helpers."""

    @classmethod
    def setup(cls, cluster_count: int, max_iterations: int = 100,
              metric: str = "euclidean", seed: int = 0):
        strat = FixedClusterCountStrategy.setup(cluster_count, metric)
        strat.end_when_iteration_count_equals(max_iterations)
        return cls(strat, seed=seed, max_iterations=max_iterations)

    @classmethod
    def setup_with_convergence(cls, cluster_count: int, rate: float,
                               metric: str = "euclidean", seed: int = 0,
                               max_iterations: int = 100):
        strat = FixedClusterCountStrategy.setup(cluster_count, metric)
        strat.end_when_distribution_variation_rate_less_than(rate)
        return cls(strat, seed=seed, max_iterations=max_iterations)
