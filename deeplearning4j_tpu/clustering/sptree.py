"""Space-partitioning tree (reference ``clustering/sptree/SpTree.java`` — the
Barnes-Hut acceleration structure for t-SNE, with ``quadtree/QuadTree.java``
as its 2-D ancestor).

Host-side: the tree is only used by the Barnes-Hut (CPU) t-SNE variant; the
TPU path computes exact repulsive forces as a fused distance matmul (see
``tsne.py``).  Supports arbitrary dimensionality d with 2^d children per cell.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["SPTree"]


class _Cell:
    __slots__ = ("center", "half", "cum_center", "count", "point_index",
                 "children", "is_leaf")

    def __init__(self, center: np.ndarray, half: np.ndarray):
        self.center = center
        self.half = half
        self.cum_center = np.zeros_like(center)
        self.count = 0
        self.point_index: Optional[int] = None
        self.children: Optional[List[Optional["_Cell"]]] = None
        self.is_leaf = True


class SPTree:
    """Barnes-Hut tree over points [N,d]; ``compute_non_edge_forces`` returns
    the t-SNE repulsive force term and normalization Z for one query point."""

    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, dtype=np.float64)
        lo, hi = self.points.min(0), self.points.max(0)
        center = (lo + hi) / 2.0
        half = np.maximum((hi - lo) / 2.0, 1e-5) * (1 + 1e-3)
        self.root = _Cell(center, half)
        for i in range(len(self.points)):
            self._insert(self.root, i)

    def _child_for(self, cell: _Cell, p: np.ndarray) -> int:
        idx = 0
        for d in range(len(p)):
            if p[d] > cell.center[d]:
                idx |= 1 << d
        return idx

    def _descend(self, cell: _Cell, i: int):
        idx = self._child_for(cell, self.points[i])
        child = cell.children[idx]
        if child is None:
            d = len(cell.center)
            offset = np.array([(1 if (idx >> j) & 1 else -1) for j in range(d)],
                              dtype=np.float64)
            child = _Cell(cell.center + offset * cell.half / 2.0, cell.half / 2.0)
            cell.children[idx] = child
        self._insert(child, i)

    def _insert(self, cell: _Cell, i: int):
        p = self.points[i]
        cell.cum_center = (cell.cum_center * cell.count + p) / (cell.count + 1)
        cell.count += 1
        if cell.is_leaf:
            if cell.point_index is None:
                cell.point_index = i
                return
            # duplicate-point guard: keep in this leaf's aggregate only
            if np.allclose(self.points[cell.point_index], p, atol=1e-12):
                return
            old = cell.point_index
            cell.point_index = None
            cell.is_leaf = False
            cell.children = [None] * (1 << len(cell.center))
            # old point descends without re-touching this cell's aggregate
            self._descend(cell, old)
            self._descend(cell, i)
        else:
            self._descend(cell, i)

    def compute_non_edge_forces(self, query_index: int, theta: float):
        """Returns (neg_force [d], Z_contribution) for point ``query_index``
        (reference ``SpTree.computeNonEdgeForces``)."""
        q = self.points[query_index]
        neg = np.zeros_like(q)
        z = 0.0
        stack = [self.root]
        while stack:
            cell = stack.pop()
            if cell is None or cell.count == 0:
                continue
            diff = q - cell.cum_center
            d2 = float(diff @ diff)
            width = float(cell.half.max() * 2.0)
            if cell.is_leaf or (d2 > 0 and width / np.sqrt(d2) < theta):
                cnt = cell.count
                if cell.is_leaf and cell.point_index == query_index:
                    cnt -= 1  # exclude self from this leaf's aggregate
                if cnt <= 0:
                    continue
                mult = 1.0 / (1.0 + d2)
                z += cnt * mult
                neg += cnt * mult * mult * diff
            else:
                stack.extend(c for c in cell.children if c is not None)
        return neg, z
