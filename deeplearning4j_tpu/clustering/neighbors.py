"""Nearest-neighbor search.

TPU-first design note: the reference's exact-NN structures (VPTree
``clustering/vptree/VPTree.java:48``, KDTree ``clustering/kdtree/KDTree.java``)
are pointer-chasing trees — the wrong shape for a systolic array.  On TPU the
idiomatic exact-kNN is a *batched distance matmul* + ``lax.top_k``: the
pairwise-distance Gram matrix rides the MXU and top-k is a fused XLA reduce.
That is the default device path here (:class:`BruteForceNN`).  The tree
structures are still provided (host-side, NumPy) because the serving tier
(``NearestNeighborsServer``, reference
``deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer.java:44``)
and Barnes-Hut t-SNE want cheap single-query exact search on CPU.
"""
from __future__ import annotations

import functools
import heapq
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BruteForceNN", "VPTree", "KDTree", "pairwise_distance"]


def _norm_rows(x):
    n = jnp.linalg.norm(x, axis=-1, keepdims=True)
    return x / jnp.maximum(n, 1e-12)


@functools.partial(jax.jit, static_argnames=("metric",))  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def pairwise_distance(queries, points, metric: str = "euclidean"):
    """[Q,D] x [N,D] -> [Q,N] distances.  euclidean/cosine/manhattan/dot.

    Euclidean uses the ||a||^2 - 2ab + ||b||^2 expansion so the cross term is
    one MXU matmul instead of a [Q,N,D] broadcast (HBM-bound).
    """
    if metric == "euclidean":
        q2 = jnp.sum(queries * queries, axis=-1)[:, None]
        p2 = jnp.sum(points * points, axis=-1)[None, :]
        cross = queries @ points.T
        return jnp.sqrt(jnp.maximum(q2 - 2.0 * cross + p2, 0.0))
    if metric == "cosine":
        return 1.0 - _norm_rows(queries) @ _norm_rows(points).T
    if metric == "manhattan":
        return jnp.sum(jnp.abs(queries[:, None, :] - points[None, :, :]), axis=-1)
    if metric == "dot":
        return -(queries @ points.T)
    raise ValueError(f"unknown metric {metric!r}")


@functools.partial(jax.jit, static_argnames=("k", "metric"))  # graftlint: disable=JX028  (clustering analytics kernel; outside the audited train/serve program set)
def _knn(queries, points, k: int, metric: str):
    d = pairwise_distance(queries, points, metric)
    neg_d, idx = jax.lax.top_k(-d, k)
    return -neg_d, idx


class BruteForceNN:
    """Exact kNN on device: distance Gram matrix (MXU) + ``lax.top_k``."""

    def __init__(self, points, metric: str = "euclidean"):
        self.points = jnp.asarray(points)
        self.metric = metric

    def query(self, queries, k: int = 1) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (distances [Q,k], indices [Q,k]); k is clamped to N."""
        queries = jnp.atleast_2d(jnp.asarray(queries))
        d, i = _knn(queries, self.points, min(k, len(self.points)), self.metric)
        return np.asarray(d), np.asarray(i)


def _host_dist(a: np.ndarray, b: np.ndarray, metric: str) -> np.ndarray:
    if metric == "euclidean":
        return np.linalg.norm(a - b, axis=-1)
    if metric == "manhattan":
        return np.sum(np.abs(a - b), axis=-1)
    if metric == "cosine":
        na = a / np.maximum(np.linalg.norm(a, axis=-1, keepdims=True), 1e-12)
        nb = b / np.maximum(np.linalg.norm(b, axis=-1, keepdims=True), 1e-12)
        return 1.0 - np.sum(na * nb, axis=-1)
    raise ValueError(metric)


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index, threshold, inside, outside):
        self.index = index
        self.threshold = threshold
        self.inside = inside
        self.outside = outside


class VPTree:
    """Vantage-point tree (reference ``clustering/vptree/VPTree.java:48``).

    Host-side exact metric tree for the serving tier; median-split on the
    distance to a randomly chosen vantage point.
    """

    def __init__(self, points, metric: str = "euclidean", seed: int = 0):
        self.points = np.asarray(points, dtype=np.float64)
        self.metric = metric
        self._rng = np.random.default_rng(seed)
        self.root = self._build(np.arange(len(self.points)))

    def _build(self, idx: np.ndarray) -> Optional[_VPNode]:
        if idx.size == 0:
            return None
        vp_pos = self._rng.integers(idx.size)
        vp = idx[vp_pos]
        rest = np.delete(idx, vp_pos)
        if rest.size == 0:
            return _VPNode(vp, 0.0, None, None)
        d = _host_dist(self.points[rest], self.points[vp], self.metric)
        med = float(np.median(d))
        inside = rest[d <= med]
        outside = rest[d > med]
        return _VPNode(vp, med, self._build(inside), self._build(outside))

    def query(self, point, k: int = 1) -> Tuple[np.ndarray, np.ndarray]:
        point = np.asarray(point, dtype=np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance

        def search(node: Optional[_VPNode]):
            if node is None:
                return
            d = float(_host_dist(self.points[node.index], point, self.metric))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            tau = -heap[0][0] if len(heap) == k else np.inf
            if d < node.threshold:
                search(node.inside)
                if d + tau >= node.threshold:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau <= node.threshold:
                    search(node.inside)

        search(self.root)
        order = sorted((-nd, i) for nd, i in heap)
        return (np.array([d for d, _ in order]),
                np.array([i for _, i in order], dtype=np.int64))


class _KDNode:
    __slots__ = ("index", "dim", "left", "right")

    def __init__(self, index, dim, left, right):
        self.index = index
        self.dim = dim
        self.left = left
        self.right = right


class KDTree:
    """k-d tree (reference ``clustering/kdtree/KDTree.java``), euclidean."""

    def __init__(self, points):
        self.points = np.asarray(points, dtype=np.float64)
        self.root = self._build(np.arange(len(self.points)), 0)

    def _build(self, idx: np.ndarray, depth: int) -> Optional[_KDNode]:
        if idx.size == 0:
            return None
        dim = depth % self.points.shape[1]
        order = idx[np.argsort(self.points[idx, dim], kind="stable")]
        mid = order.size // 2
        return _KDNode(order[mid], dim,
                       self._build(order[:mid], depth + 1),
                       self._build(order[mid + 1:], depth + 1))

    def query(self, point, k: int = 1) -> Tuple[np.ndarray, np.ndarray]:
        point = np.asarray(point, dtype=np.float64)
        heap: List[Tuple[float, int]] = []

        def search(node: Optional[_KDNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - point))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = point[node.dim] - self.points[node.index, node.dim]
            near, far = (node.left, node.right) if diff <= 0 else (node.right, node.left)
            search(near)
            tau = -heap[0][0] if len(heap) == k else np.inf
            if abs(diff) <= tau:
                search(far)

        search(self.root)
        order = sorted((-nd, i) for nd, i in heap)
        return (np.array([d for d, _ in order]),
                np.array([i for _, i in order], dtype=np.int64))
