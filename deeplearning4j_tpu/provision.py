"""Fleet provisioning: TPU pod/VM cluster setup command generation.

Reference ``deeplearning4j-aws`` (SURVEY.md §2.4): ``ec2/provision/
ClusterSetup.java`` boots an EC2 fleet and ``s3/`` moves artifacts.  The
TPU-native equivalent provisions Cloud TPU slices: a ``ClusterSpec``
describes the fleet, ``TpuClusterSetup`` emits (and optionally executes)
the exact ``gcloud`` commands, and ``StorageTransfer`` wraps ``gsutil``
for the S3-uploader role.  Command generation is pure (testable,
zero-egress); execution is explicit opt-in, mirroring the reference's
side-effecting provisioner.
"""
from __future__ import annotations

import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["ClusterSpec", "TpuClusterSetup", "StorageTransfer"]


@dataclass
class ClusterSpec:
    """One TPU slice / fleet description (the EC2 fleet-spec role)."""
    name: str
    zone: str = "us-central2-b"
    accelerator_type: str = "v5e-64"
    runtime_version: str = "tpu-ubuntu2204-base"
    project: Optional[str] = None
    preemptible: bool = False
    network: Optional[str] = None
    tags: Dict[str, str] = field(default_factory=dict)


class TpuClusterSetup:
    """Generate/execute provisioning commands (reference
    ``ClusterSetup.java`` — its ``provision()`` boots the fleet; here
    ``apply()`` only runs when ``execute=True``)."""

    def __init__(self, spec: ClusterSpec):
        self.spec = spec

    def _base(self) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm"]
        return cmd

    def create_command(self) -> List[str]:
        s = self.spec
        cmd = self._base() + ["create", s.name, f"--zone={s.zone}",
                              f"--accelerator-type={s.accelerator_type}",
                              f"--version={s.runtime_version}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        if s.preemptible:
            cmd.append("--preemptible")
        if s.network:
            cmd.append(f"--network={s.network}")
        if s.tags:
            # gcloud --labels is a dict flag: repeating it overrides, so
            # all pairs must go in one comma-joined occurrence
            pairs = ",".join(f"{k}={v}" for k, v in sorted(s.tags.items()))
            cmd.append(f"--labels={pairs}")
        return cmd

    def delete_command(self) -> List[str]:
        s = self.spec
        cmd = self._base() + ["delete", s.name, f"--zone={s.zone}",
                              "--quiet"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def ssh_command(self, worker: str = "all",
                    remote_command: Optional[str] = None) -> List[str]:
        s = self.spec
        cmd = self._base() + ["ssh", s.name, f"--zone={s.zone}",
                              f"--worker={worker}"]
        if remote_command:
            cmd += ["--command", remote_command]
        return cmd

    def describe_command(self) -> List[str]:
        s = self.spec
        return self._base() + ["describe", s.name, f"--zone={s.zone}"]

    def render(self) -> str:
        """The full provisioning script as shell text (audit artifact)."""
        return "\n".join(shlex.join(c) for c in (
            self.create_command(), self.describe_command()))

    def apply(self, execute: bool = False, timeout: float = 600):
        """Run the create command.  execute=False (default) returns the
        command without side effects."""
        cmd = self.create_command()
        if not execute:
            return cmd
        return subprocess.run(cmd, check=True, capture_output=True,
                              timeout=timeout)


class StorageTransfer:
    """gsutil up/down-loader (reference ``aws/s3/uploader``)."""

    def __init__(self, bucket: str):
        if not bucket.startswith("gs://"):
            bucket = f"gs://{bucket}"
        self.bucket = bucket.rstrip("/")

    def upload_command(self, local_path: str, remote_key: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r", local_path,
                f"{self.bucket}/{remote_key}"]

    def download_command(self, remote_key: str, local_path: str) -> List[str]:
        return ["gsutil", "-m", "cp", "-r",
                f"{self.bucket}/{remote_key}", local_path]

    def run(self, cmd: List[str], execute: bool = False, timeout: float = 600):
        if not execute:
            return cmd
        return subprocess.run(cmd, check=True, capture_output=True,
                              timeout=timeout)
