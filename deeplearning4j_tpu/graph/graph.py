"""In-memory graph structure + walk iterators.

Reference ``deeplearning4j-graph``: ``graph/api/{IGraph,Vertex,Edge,
NoEdgeHandling}.java``, ``graph/graph/Graph.java`` (adjacency-list graph),
``graph/iterator/{RandomWalkIterator,WeightedRandomWalkIterator}.java``, and
the edge-list loaders in ``graph/data/impl/``.

Walk generation is host-side (it feeds the vocab/batcher pipeline); the
device only sees the resulting index batches via DeepWalk's skip-gram.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Generic, Iterable, Iterator, List, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


class NoEdgesException(Exception):
    """Walk hit a vertex with no outgoing edges under EXCEPTION handling
    (reference ``graph/exception/NoEdgesException.java``)."""


@dataclass
class Vertex(Generic[T]):
    """Reference ``graph/api/Vertex.java``: index + attached value."""
    idx: int
    value: Optional[T] = None


@dataclass
class Edge(Generic[T]):
    """Reference ``graph/api/Edge.java``."""
    frm: int
    to: int
    value: Optional[T] = None
    directed: bool = False

    @property
    def weight(self) -> float:
        return 1.0 if self.value is None else float(self.value)


class NoEdgeHandling:
    """Reference ``graph/api/NoEdgeHandling.java``."""
    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class Graph(Generic[T]):
    """Adjacency-list graph (reference ``graph/graph/Graph.java``)."""

    def __init__(self, n_vertices: int = 0,
                 allow_multiple_edges: bool = True,
                 vertices: Optional[Sequence[Vertex]] = None):
        if vertices is not None:
            self._vertices = list(vertices)
        else:
            self._vertices = [Vertex(i) for i in range(n_vertices)]
        self.allow_multiple_edges = allow_multiple_edges
        self._edges: List[List[Edge]] = [[] for _ in self._vertices]

    # -- construction --------------------------------------------------------
    def add_vertex(self, value: Optional[T] = None) -> Vertex:
        v = Vertex(len(self._vertices), value)
        self._vertices.append(v)
        self._edges.append([])
        return v

    def add_edge(self, frm: int, to: int, value=None,
                 directed: bool = False) -> None:
        e = Edge(frm, to, value, directed)
        if not self.allow_multiple_edges and any(
                x.to == to for x in self._edges[frm]):
            return
        self._edges[frm].append(e)
        if not directed and frm != to:
            self._edges[to].append(Edge(to, frm, value, directed))

    # -- queries -------------------------------------------------------------
    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def get_edges_out(self, idx: int) -> List[Edge]:
        return list(self._edges[idx])

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._edges[idx])

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        return [e.to for e in self._edges[idx]]

    def get_random_connected_vertex(self, idx: int, rng) -> int:
        edges = self._edges[idx]
        if not edges:
            raise NoEdgesException(f"vertex {idx} has no outgoing edges")
        return edges[int(rng.integers(0, len(edges)))].to

    def degrees(self) -> np.ndarray:
        return np.array([len(e) for e in self._edges], dtype=np.int64)


# ---------------------------------------------------------------------------
# walk iterators
# ---------------------------------------------------------------------------

class GraphWalkIterator:
    """Stream of vertex-index walks (reference ``GraphWalkIterator.java``).
    Restartable: each ``__iter__`` regenerates walks with a fresh sub-seed."""

    def __init__(self, graph: Graph, walk_length: int,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 seed: int = 123):
        self.graph = graph
        self.walk_length = walk_length
        self.no_edge_handling = no_edge_handling
        self.seed = seed
        self._epoch = 0

    def _next_vertex(self, cur: int, rng) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng((self.seed, self._epoch))
        self._epoch += 1
        order = rng.permutation(self.graph.num_vertices())
        for start in order:
            walk = [int(start)]
            cur = int(start)
            for _ in range(self.walk_length):
                if self.graph.get_vertex_degree(cur) == 0:
                    if self.no_edge_handling == \
                            NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                        raise NoEdgesException(
                            f"vertex {cur} has no edges mid-walk")
                    walk.append(cur)  # self loop
                    continue
                cur = self._next_vertex(cur, rng)
                walk.append(cur)
            yield walk


class RandomWalkIterator(GraphWalkIterator):
    """Uniform random walks (reference ``RandomWalkIterator.java``)."""

    def _next_vertex(self, cur: int, rng) -> int:
        return self.graph.get_random_connected_vertex(cur, rng)


class WeightedRandomWalkIterator(GraphWalkIterator):
    """Edge-weight-proportional walks (``WeightedRandomWalkIterator.java``)."""

    def _next_vertex(self, cur: int, rng) -> int:
        edges = self.graph.get_edges_out(cur)
        weights = np.array([e.weight for e in edges], dtype=np.float64)
        if (weights < 0).any():
            raise ValueError(
                f"vertex {cur} has negative edge weights; weighted walks "
                "need non-negative weights")
        s = weights.sum()
        if s <= 0:
            return edges[int(rng.integers(0, len(edges)))].to
        return edges[int(rng.choice(len(edges), p=weights / s))].to


# ---------------------------------------------------------------------------
# loaders (reference graph/data/impl/)
# ---------------------------------------------------------------------------

def load_edge_list(path: str, n_vertices: Optional[int] = None,
                   delimiter: str = ",", directed: bool = False,
                   weighted: bool = False) -> Graph:
    """Edge-list file → Graph (reference ``DelimitedEdgeLineProcessor`` /
    ``WeightedEdgeLineProcessor`` + ``GraphLoader``).  Lines starting with
    ``//`` or ``#`` are comments."""
    edges = []
    max_idx = -1
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(("//", "#")):
                continue
            parts = [p.strip() for p in line.split(delimiter)]
            frm, to = int(parts[0]), int(parts[1])
            w = float(parts[2]) if weighted and len(parts) > 2 else None
            edges.append((frm, to, w))
            max_idx = max(max_idx, frm, to)
    g = Graph(n_vertices if n_vertices is not None else max_idx + 1)
    for frm, to, w in edges:
        g.add_edge(frm, to, w, directed=directed)
    return g
