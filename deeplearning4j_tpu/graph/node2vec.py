"""Node2Vec: biased second-order random walks + skip-gram embeddings.

Reference ``deeplearning4j-nlp-parent/.../models/node2vec/`` (Node2Vec atop
the SequenceVectors engine).  The walk bias follows the node2vec paper
(Grover & Leskovec 2016): from edge (t -> v), the unnormalized probability
of stepping to x is

    w(v,x)/p  if x == t            (return)
    w(v,x)    if x adjacent to t   (BFS-ish)
    w(v,x)/q  otherwise            (DFS-ish)

Walk generation is host-side (feeds the vocab/batcher pipeline); training
is DeepWalk's jitted hierarchical-softmax skip-gram step.
"""
from __future__ import annotations

from typing import Iterator, List

import numpy as np

from .deepwalk import DeepWalk
from .graph import Graph, GraphWalkIterator, NoEdgeHandling, NoEdgesException

__all__ = ["Node2Vec", "Node2VecWalkIterator"]


class Node2VecWalkIterator(GraphWalkIterator):
    """Second-order biased walks (p = return parameter, q = in-out
    parameter; p = q = 1 degenerates to RandomWalkIterator)."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 seed: int = 123):
        super().__init__(graph, walk_length, no_edge_handling, seed)
        if p <= 0 or q <= 0:
            raise ValueError(f"p and q must be positive, got p={p} q={q}")
        self.p = float(p)
        self.q = float(q)
        # neighbor sets for the O(1) "is x adjacent to t" test
        self._nbrs = [set(graph.get_connected_vertex_indices(i))
                      for i in range(graph.num_vertices())]

    def _step(self, prev: int, cur: int, rng) -> int:
        edges = self.graph.get_edges_out(cur)
        if len(edges) == 1:
            return edges[0].to
        w = np.empty(len(edges), np.float64)
        prev_nbrs = self._nbrs[prev]
        for i, e in enumerate(edges):
            wt = e.weight
            if e.to == prev:
                w[i] = wt / self.p
            elif e.to in prev_nbrs:
                w[i] = wt
            else:
                w[i] = wt / self.q
        s = w.sum()
        if s <= 0:
            return edges[int(rng.integers(0, len(edges)))].to
        return edges[int(rng.choice(len(edges), p=w / s))].to

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng((self.seed, self._epoch))
        self._epoch += 1
        g = self.graph
        for start in rng.permutation(g.num_vertices()):
            cur = int(start)
            walk = [cur]
            prev = -1
            for _ in range(self.walk_length):
                deg = g.get_vertex_degree(cur)
                if deg == 0:
                    if self.no_edge_handling == \
                            NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                        raise NoEdgesException(
                            f"vertex {cur} has no edges mid-walk")
                    walk.append(cur)
                    continue
                if prev < 0:  # first step: uniform/weight-proportional
                    nxt = g.get_random_connected_vertex(cur, rng)
                else:
                    nxt = self._step(prev, cur, rng)
                prev, cur = cur, int(nxt)
                walk.append(cur)
            yield walk


class Node2Vec(DeepWalk):
    """Node2Vec trainer: DeepWalk with p/q-biased walk generation
    (reference ``models/node2vec/Node2Vec.java``)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 p: float = 1.0, q: float = 1.0,
                 learning_rate: float = 0.025, seed: int = 123,
                 batch_size: int = 512, epochs: int = 1):
        super().__init__(vector_size=vector_size, window_size=window_size,
                         learning_rate=learning_rate, seed=seed,
                         batch_size=batch_size, epochs=epochs)
        self.p = p
        self.q = q

    def fit(self, walks=None, walk_length: int = 40) -> None:
        if isinstance(walks, Graph):
            if self.graph is None:
                self.initialize(walks)
            walks = Node2VecWalkIterator(walks, walk_length, p=self.p,
                                         q=self.q, seed=self.seed)
        super().fit(walks, walk_length)
