"""DeepWalk: skip-gram embeddings over random graph walks.

Reference ``graph/models/deepwalk/DeepWalk.java:31`` (fit :95-152) +
``GraphHuffman.java`` (Huffman over vertex degrees) + ``GraphVectors`` query
API (``models/embeddings/GraphVectorsImpl.java``).  Rides the NLP
SequenceVectors engine: walks become token sequences, the Huffman tree is
built from vertex degrees (not corpus counts), and training is the jitted
hierarchical-softmax skip-gram step.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..nlp.lookup_table import InMemoryLookupTable
from ..nlp.sequence_vectors import SequenceVectors
from ..nlp.vocab import VocabCache, VocabWord, build_huffman
from .graph import Graph, GraphWalkIterator, RandomWalkIterator


class DeepWalk(SequenceVectors):
    """GraphVectors trainer (reference ``DeepWalk.java``).

    ``initialize(graph)`` builds the degree-based Huffman vocab;
    ``fit(walk_iterator)`` trains on one pass of walks (call repeatedly or
    pass ``epochs>1`` for more).
    """

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, seed: int = 123,
                 batch_size: int = 512, epochs: int = 1):
        super().__init__(layer_size=vector_size, window=window_size,
                         learning_rate=learning_rate, negative=0,
                         use_hierarchic_softmax=True, epochs=epochs,
                         batch_size=batch_size, seed=seed)
        self.graph: Optional[Graph] = None
        self._walks: Optional[GraphWalkIterator] = None

    @property
    def vector_size(self) -> int:
        return self.layer_size

    # -- setup ---------------------------------------------------------------
    def initialize(self, graph: Graph) -> None:
        """Degree-based vocab + Huffman (reference ``GraphHuffman``: codes
        weighted by vertex degree so hub vertices get short paths)."""
        self.graph = graph
        degrees = graph.degrees()
        cache = VocabCache()
        # vertex i <-> token str(i); index order preserved (no frequency sort
        # — GraphVectors queries are by vertex index)
        for i in range(graph.num_vertices()):
            cache.add_token(VocabWord(str(i), count=max(int(degrees[i]), 1)))
        cache.total_word_count = int(np.maximum(degrees, 1).sum())
        build_huffman(cache.vocab_words())
        self.vocab = cache
        self.lookup_table = InMemoryLookupTable(
            cache, self.layer_size, seed=self.seed, use_hs=True, negative=0)
        self.lookup_table.reset_weights()

    # -- training ------------------------------------------------------------
    def _sequences(self):
        for walk in self._walks:
            yield [str(v) for v in walk]

    def fit(self, walks=None, walk_length: int = 40) -> None:
        """Train on a walk iterator; a bare Graph gets a default
        RandomWalkIterator (reference ``fit(IGraph, int)`` overload)."""
        if isinstance(walks, Graph):
            if self.graph is None:
                self.initialize(walks)
            walks = RandomWalkIterator(walks, walk_length, seed=self.seed)
        if walks is not None:
            self._walks = walks
        if self.vocab is None:
            raise ValueError("call initialize(graph) before fit()")
        if self._walks is None:
            raise ValueError("no walk iterator provided")
        super().fit()

    # -- GraphVectors query API ----------------------------------------------
    def get_vertex_vector(self, idx: int) -> np.ndarray:
        return np.asarray(self.lookup_table.syn0[idx])

    def similarity_vertices(self, a: int, b: int) -> float:
        return self.similarity(str(a), str(b))

    def vertices_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.words_nearest(str(idx), top_n=top_n)]

    def num_vertices(self) -> int:
        return self.vocab.num_words()
