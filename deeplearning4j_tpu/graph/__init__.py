"""Graph embeddings: in-memory graph, walk iterators, DeepWalk.

TPU-native re-design of reference ``deeplearning4j-graph`` (SURVEY.md §2.6).
"""
from .deepwalk import DeepWalk
from .node2vec import Node2Vec, Node2VecWalkIterator
from .graph import (Edge, Graph, GraphWalkIterator, NoEdgeHandling,
                    NoEdgesException, RandomWalkIterator, Vertex,
                    WeightedRandomWalkIterator, load_edge_list)

__all__ = ["DeepWalk", "Node2Vec", "Node2VecWalkIterator", "Edge", "Graph", "GraphWalkIterator", "NoEdgeHandling",
           "NoEdgesException", "RandomWalkIterator", "Vertex",
           "WeightedRandomWalkIterator", "load_edge_list"]
