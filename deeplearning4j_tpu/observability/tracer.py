"""Span-based tracer: nested spans with monotonic timing, cross-thread /
cross-process context propagation, and optional bridging into
``jax.profiler.TraceAnnotation`` so spans land on Xprof timelines.

The model is deliberately small (a working subset of OpenTelemetry's):

- a **Span** is a named interval with attributes, a ``trace_id`` shared
  by everything descending from one root, and a ``parent_id``;
- the **active span stack** is thread-local, so ``span()`` nests
  naturally inside one thread;
- a **SpanContext** is the serializable (trace_id, span_id) pair a
  parent hands to another thread (``parallel/master.py`` worker pools)
  or another process (``parallel/master_mp.py`` puts it in the job
  spec); ``attach(ctx)`` re-roots the local stack under the remote
  parent.

Tracing is OFF by default (unlike the metrics registry, which stays on
— spans allocate objects and read clocks, counters are plain float
adds).  A disabled tracer short-circuits ``span()`` to a shared no-op
context manager: no object allocation, no clock reads, no device syncs
ever.
"""
from __future__ import annotations

import contextlib
import os
import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .clock import monotonic_s, wall_s
from .registry import MetricsRegistry, default_registry

__all__ = ["Span", "SpanContext", "Tracer", "get_tracer",
           "set_default_tracer"]

# span-duration histogram bounds: phase timings range from sub-ms host
# work to multi-second aggregation rounds
_SPAN_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                 10.0, 60.0)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class SpanContext:
    """Serializable propagation handle: everything a child span in
    another thread/process needs to join the trace."""
    trace_id: str
    span_id: str

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Dict[str, str]) -> "SpanContext":
        return cls(trace_id=str(d["trace_id"]), span_id=str(d["span_id"]))


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    attributes: Dict[str, Any] = field(default_factory=dict)
    start_wall_s: float = 0.0
    _start_mono: float = 0.0
    duration_s: Optional[float] = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    @property
    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_wall_s": self.start_wall_s,
                "duration_s": self.duration_s,
                "attributes": dict(self.attributes)}


class _RemoteParent:
    """Stack entry representing a span living in another thread/process —
    context-only, never timed or recorded locally."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, ctx: SpanContext):
        self.trace_id = ctx.trace_id
        self.span_id = ctx.span_id


@contextlib.contextmanager
def _noop_cm():
    yield None


class Tracer:
    """Create with ``enabled=True`` (or call :func:`get_tracer` after
    ``set_default_tracer``) to record spans.

    ``registry``: span durations land in a ``span_seconds{name=...}``
    histogram there (defaults to the process-global registry).
    ``bridge_xprof``: wrap every span in a
    ``jax.profiler.TraceAnnotation`` so host-side phases line up with
    device ops in Xprof captures (imports jax lazily — the tracer stays
    dependency-free when the bridge is off).
    ``max_finished``: ring buffer of completed spans kept for
    inspection/tests; 0 keeps none.
    """

    def __init__(self, enabled: bool = False,
                 registry: Optional[MetricsRegistry] = None,
                 bridge_xprof: bool = False,
                 max_finished: int = 1024,
                 event_log=None):
        self._enabled = enabled
        self._registry = registry
        self._bridge_xprof = bridge_xprof
        self._max_finished = max_finished
        self._event_log = event_log
        self._tls = threading.local()
        self._finished: List[Span] = []
        self._finished_lock = threading.Lock()

    # -- state ---------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> "Tracer":
        self._enabled = True
        return self

    def disable(self) -> "Tracer":
        self._enabled = False
        return self

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span(self) -> Optional[Span]:
        st = self._stack()
        for entry in reversed(st):
            if isinstance(entry, Span):
                return entry
        return None

    def current_context(self) -> Optional[SpanContext]:
        """Propagation handle for the innermost active span (remote or
        local); None outside any span or when disabled."""
        st = self._stack()
        if not st:
            return None
        top = st[-1]
        return SpanContext(trace_id=top.trace_id, span_id=top.span_id)

    @property
    def finished_spans(self) -> List[Span]:
        with self._finished_lock:
            return list(self._finished)

    def clear_finished(self) -> None:
        with self._finished_lock:
            self._finished.clear()

    # -- span lifecycle ------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        """Open a nested span; yields the Span (or None when disabled)."""
        if not self._enabled:
            with _noop_cm() as nothing:
                yield nothing
            return
        st = self._stack()
        parent = st[-1] if st else None
        sp = Span(name=name,
                  trace_id=parent.trace_id if parent else _new_id(),
                  span_id=_new_id(),
                  parent_id=parent.span_id if parent else None,
                  attributes=dict(attributes),
                  start_wall_s=wall_s(),
                  _start_mono=monotonic_s())
        st.append(sp)
        annotation = None
        if self._bridge_xprof:
            try:
                import jax
                annotation = jax.profiler.TraceAnnotation(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        try:
            yield sp
        finally:
            if annotation is not None:
                try:
                    annotation.__exit__(None, None, None)
                except Exception:
                    pass
            sp.duration_s = monotonic_s() - sp._start_mono
            if st and st[-1] is sp:
                st.pop()
            else:  # tolerate out-of-order exits from generator teardown
                try:
                    st.remove(sp)
                except ValueError:
                    pass
            self._record(sp)

    @contextlib.contextmanager
    def attach(self, ctx: Optional[SpanContext]):
        """Continue a trace started elsewhere: spans opened inside this
        context parent onto ``ctx`` (worker threads get the master's
        context; worker processes get it from the serialized job spec).
        A None ctx (or a disabled tracer) is a no-op, so call sites can
        propagate unconditionally."""
        if not self._enabled or ctx is None:
            with _noop_cm():
                yield self
            return
        st = self._stack()
        entry = _RemoteParent(ctx)
        st.append(entry)
        try:
            yield self
        finally:
            try:
                st.remove(entry)
            except ValueError:
                pass

    # -- sinks ---------------------------------------------------------------
    def _record(self, sp: Span) -> None:
        if self._max_finished:
            with self._finished_lock:
                self._finished.append(sp)
                if len(self._finished) > self._max_finished:
                    del self._finished[:len(self._finished)
                                       - self._max_finished]
        reg = self._registry if self._registry is not None \
            else default_registry()
        if reg.enabled:
            reg.histogram("span_seconds",
                          "Tracer span durations by span name",
                          ("name",), buckets=_SPAN_BUCKETS) \
               .labels(sp.name).observe(sp.duration_s)
        if self._event_log is not None:
            self._event_log.emit("span", **sp.to_dict())
        # finished spans also land in the flight recorder's span ring so
        # a crash dump carries the recent execution timeline
        from .recorder import get_flight_recorder
        rec = get_flight_recorder()
        if rec is not None:
            rec.record_span(sp)


# env opt-in: DL4J_TPU_TRACE=1 enables the default tracer at import time
# (the knob production pods flip without code changes); =xprof also
# bridges spans into profiler captures.
_env = os.environ.get("DL4J_TPU_TRACE", "")
_default_tracer = Tracer(enabled=bool(_env),
                         bridge_xprof=_env.lower() == "xprof")
_default_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer every built-in instrumentation point
    uses unless handed an explicit instance.  Disabled by default."""
    return _default_tracer


def set_default_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer; returns the previous one."""
    global _default_tracer
    with _default_tracer_lock:
        prev, _default_tracer = _default_tracer, tracer
    return prev
