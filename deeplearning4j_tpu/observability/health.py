"""Streaming training/serving health monitor: anomaly detection over the
signals the registry already collects, reacting *before* a run is wasted.

The registry records what happened; nothing watches it.  A NaN gradient
at step 40k silently poisons every later step, a loss spike marks the
moment divergence started, a throughput collapse burns budget at full
allocation — all visible in ``/metrics`` *if a human is looking*.
:class:`HealthMonitor` is the machine that looks:

==================  ======================================  ==============
detector            signal                                  detection kind
==================  ======================================  ==============
non-finite          loss / grad-norm is NaN or +-Inf        ``nan_loss`` /
                                                            ``nan_grad``
EWMA z-score spike  loss / grad-norm vs running mean+var    ``loss_spike`` /
                                                            ``grad_spike``
throughput          steady examples/sec EWMA collapses      ``throughput_``
regression          below a fraction of the peak EWMA       ``regression``
padding drift       padding-ratio EWMA drifts off its       ``padding_``
                    warmed baseline                         ``drift``
serving p99         sliding-window p99 over a target        ``serving_p99``
                    (:class:`~.quantiles.LatencyWindow`)
shed rate           shed fraction of recent admissions      ``shed_rate``
==================  ======================================  ==============

Every detection emits a structured event (:func:`~.events.emit_event` +
the flight-recorder ``health`` channel), lands in
``health_detections_total{kind}``, and flips :meth:`state` to
``degraded`` — which both HTTP servers surface as a third ``/health``
state between ``ok`` and ``unready`` (degraded = still serving, but a
human should look).  Detections can also **act**: a bound checkpoint
hook (``fit`` binds its :class:`FitCheckpointer`) takes an immediate
crash-consistent save — the artifact from *before* the divergence — and
with ``stop_training=True`` (opt-in) the fit loop halts cleanly through
the same contract the terminations path uses.

False-positive posture: every statistical detector warms up on real
data before it may fire (``warmup_steps`` / ``min_samples``), spikes are
measured in EWMA standard deviations with a variance floor (a perfectly
flat loss cannot divide by zero into a false alarm), and same-kind
detections within ``dedupe_s`` merge into one (a NaN run is ONE
incident, not ten thousand).
"""
from __future__ import annotations

import collections
import math
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .clock import monotonic_s, wall_s
from .events import emit_event
from .quantiles import LatencyWindow
from .registry import MetricsRegistry, default_registry

__all__ = ["HealthConfig", "HealthMonitor", "Detection",
           "HealthTermination", "get_health_monitor", "set_health_monitor"]

# detection kinds whose cause does not decay with time: a NaN in the
# params poisons everything after it, so degraded sticks until clear()
_STICKY_KINDS = frozenset(("nan_loss", "nan_grad"))


@dataclass(frozen=True)
class HealthConfig:
    """Detector thresholds + reaction policy; defaults are deliberately
    conservative (few false positives on noisy-but-healthy runs)."""

    # EWMA spike detectors (loss / grad-norm)
    ewma_alpha: float = 0.05
    z_threshold: float = 8.0
    warmup_steps: int = 20
    # the fit loops fetch the grad norm off-device only every Nth step:
    # it is the monitor's one per-step device read (~15us on CPU), and a
    # NaN gradient poisons the params so the NEXT step's loss — checked
    # every step for free — goes NaN anyway; subsampling trades at most
    # grad_check_every steps of detection latency for <2% step overhead
    grad_check_every: int = 4
    # throughput regression: steady EWMA below ratio * peak EWMA
    throughput_floor_ratio: float = 0.5
    throughput_warmup: int = 20
    # MFU regression: sampled-fence MFU EWMA (StepProfiler) below ratio *
    # peak EWMA; fences arrive 1-in-sample_every steps, so the warmup is
    # counted in SAMPLES, not steps
    mfu_floor_ratio: float = 0.5
    mfu_warmup: int = 8
    # padding drift: |ewma - baseline| above this absolute ratio delta
    padding_drift: float = 0.25
    # serving detectors
    serving_window: int = 256
    serving_min_samples: int = 32
    p99_target_ms: Optional[float] = None
    shed_rate_threshold: float = 0.5
    # generation detectors: time-to-first-token and inter-token latency
    # p99 over their own sliding windows (the decode engine feeds them)
    ttft_p99_target_ms: Optional[float] = None
    itl_p99_target_ms: Optional[float] = None
    # reaction policy
    degraded_cooldown_s: float = 300.0   # non-sticky detections age out
    dedupe_s: float = 30.0               # same-kind merge window
    checkpoint_on_detection: bool = True
    stop_training: bool = False          # opt-in: halt fit on detection


@dataclass
class Detection:
    """One confirmed anomaly (possibly merging a same-kind burst)."""

    kind: str
    reason: str
    value: Optional[float] = None
    threshold: Optional[float] = None
    step: Optional[int] = None
    ts: float = field(default_factory=wall_s)
    count: int = 1
    _mono: float = field(default_factory=monotonic_s, repr=False)

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "reason": self.reason,
                "value": self.value, "threshold": self.threshold,
                "step": self.step, "ts": self.ts, "count": self.count}


class _Ewma:
    """Exponentially-weighted mean + variance (West's update)."""

    __slots__ = ("alpha", "n", "mean", "var")

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> None:
        x = float(x)
        if self.n == 0:
            self.mean, self.var = x, 0.0
        else:
            d = x - self.mean
            self.mean += self.alpha * d
            self.var = (1.0 - self.alpha) * (self.var
                                             + self.alpha * d * d)
        self.n += 1

    def z(self, x: float) -> float:
        # variance floor: a near-constant signal must not turn numeric
        # dust into an infinite z-score
        std = max(math.sqrt(self.var), 1e-3 * (abs(self.mean) + 1e-6))
        return (float(x) - self.mean) / std

    def spikes_above(self, x: float, z_threshold: float) -> bool:
        """``z(x) > z_threshold`` without the sqrt: the fit loop asks
        this every step, so the healthy path is two multiplies and two
        compares (``d > 0 and d² > z²·max(var, floor²)`` is exactly the
        threshold test on the floored std)."""
        d = x - self.mean
        if d <= 0.0:
            return False
        floor = 1e-3 * (abs(self.mean) + 1e-6)
        v = self.var if self.var > floor * floor else floor * floor
        return d * d > z_threshold * z_threshold * v


class HealthMonitor:
    """Attach globally (``set_health_monitor(HealthMonitor())``) and the
    fit loops, serving admission, and HTTP ``/health`` pick it up; or
    inject an instance where isolation matters (tests).  All entry
    points are thread-safe — the train loop, serving request threads,
    and health probes feed/read one monitor concurrently."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None):
        self.config = config or HealthConfig()
        self._registry = registry
        self._recorder = recorder
        self._lock = threading.Lock()
        self._loss = _Ewma(self.config.ewma_alpha)
        self._gnorm = _Ewma(self.config.ewma_alpha)
        self._eps = _Ewma(self.config.ewma_alpha)      # examples/sec
        self._eps_peak = 0.0
        self._mfu = _Ewma(self.config.ewma_alpha)      # sampled-fence MFU
        self._mfu_peak = 0.0
        self._pad = _Ewma(self.config.ewma_alpha)
        self._pad_baseline: Optional[float] = None
        self._steps = 0
        self._latency = LatencyWindow(self.config.serving_window)
        self._ttft = LatencyWindow(self.config.serving_window)
        self._itl = LatencyWindow(self.config.serving_window)
        self._shed_ring: collections.deque = collections.deque(
            maxlen=self.config.serving_window)
        self._detections: collections.deque = collections.deque(maxlen=64)
        self._by_kind: Dict[str, Detection] = {}
        self._stop = False
        self._save_fn = None
        self._saved_kinds: set = set()
        self.checkpoint_saves = 0

    # -- plumbing ------------------------------------------------------------
    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else default_registry()

    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from .recorder import get_flight_recorder
        return get_flight_recorder()

    def bind_checkpoint(self, save_fn) -> None:
        """Bind ``save_fn(detection) -> path`` — called once per (deduped)
        detection when ``checkpoint_on_detection`` is set.  ``fit`` binds
        its checkpointer so a detection leaves a crash-consistent save
        from before the damage spreads."""
        self._save_fn = save_fn

    # -- detection core ------------------------------------------------------
    def _detect(self, kind: str, reason: str, value: Optional[float] = None,
                threshold: Optional[float] = None,
                step: Optional[int] = None) -> Optional[Detection]:
        """Register one anomaly; returns the Detection, or None when it
        merged into a same-kind detection inside the dedupe window."""
        now = monotonic_s()
        with self._lock:
            prev = self._by_kind.get(kind)
            if prev is not None and now - prev._mono < self.config.dedupe_s:
                prev.count += 1
                prev._mono = now
                return None
            det = Detection(kind=kind, reason=reason, value=value,
                            threshold=threshold, step=step)
            self._by_kind[kind] = det
            self._detections.append(det)
        reg = self._reg()
        if reg.enabled:
            reg.counter("health_detections_total",
                        "Anomalies confirmed by the health monitor",
                        ("kind",)).labels(kind).inc()
            reg.gauge("health_degraded",
                      "1 while the health monitor reports degraded").set(1)
        emit_event("health_detection", **det.to_dict())
        rec = self._rec()
        if rec is not None:
            rec.record("health", "detection", **det.to_dict())
        if self.config.stop_training:
            self._stop = True
        if self._save_fn is not None and self.config.checkpoint_on_detection \
                and kind not in self._saved_kinds:
            # one emergency save per kind: a sticky detection re-firing
            # every dedupe_s must not keep saving (possibly poisoned)
            # params until the manager's keep_last window holds nothing
            # from before the incident
            self._saved_kinds.add(kind)
            try:
                self._save_fn(det)
                self.checkpoint_saves += 1
            except Exception:
                pass   # a failed emergency save must not kill the step
        return det

    # -- training-side observers --------------------------------------------
    def observe_step(self, loss: Optional[float] = None,
                     grad_norm: Optional[float] = None,
                     examples_per_sec: Optional[float] = None,
                     padding_ratio: Optional[float] = None,
                     step: Optional[int] = None) -> List[Detection]:
        """Feed one training step's host-side signals; returns any NEW
        detections (deduped same-kind repeats return empty).  This runs
        inside the train step loop, so the healthy path is kept to EWMA
        updates and square-compare spike checks — no sqrt, no closures,
        no allocation beyond the (usually empty) result list."""
        cfg = self.config
        out: List[Detection] = []
        self._steps += 1
        if loss is not None:
            loss = float(loss)
            ew = self._loss
            if not math.isfinite(loss):
                d = self._detect("nan_loss", "non-finite training loss",
                                 value=loss, step=step)
                if d is not None:
                    out.append(d)
            else:
                if ew.n >= cfg.warmup_steps and \
                        ew.spikes_above(loss, cfg.z_threshold):
                    d = self._detect(
                        "loss_spike",
                        f"loss {loss:.6g} is {ew.z(loss):.1f} EWMA std devs "
                        f"above mean {ew.mean:.6g}",
                        value=loss, threshold=cfg.z_threshold, step=step)
                    if d is not None:
                        out.append(d)
                ew.update(loss)
        if grad_norm is not None:
            g = float(grad_norm)
            ew = self._gnorm
            if not math.isfinite(g):
                d = self._detect("nan_grad",
                                 "non-finite gradient global norm",
                                 value=g, step=step)
                if d is not None:
                    out.append(d)
            else:
                if ew.n >= cfg.warmup_steps and \
                        ew.spikes_above(g, cfg.z_threshold):
                    d = self._detect(
                        "grad_spike",
                        f"grad norm {g:.6g} is {ew.z(g):.1f} EWMA std devs "
                        f"above mean {ew.mean:.6g}",
                        value=g, threshold=cfg.z_threshold, step=step)
                    if d is not None:
                        out.append(d)
                ew.update(g)
        if examples_per_sec is not None and examples_per_sec > 0:
            ew = self._eps
            ew.update(examples_per_sec)
            if ew.n >= cfg.throughput_warmup:
                if ew.mean > self._eps_peak:
                    self._eps_peak = ew.mean
                floor = cfg.throughput_floor_ratio * self._eps_peak
                if self._eps_peak > 0 and ew.mean < floor:
                    d = self._detect(
                        "throughput_regression",
                        f"steady throughput {ew.mean:.1f} ex/s fell "
                        f"below {cfg.throughput_floor_ratio:.0%} of peak "
                        f"{self._eps_peak:.1f}",
                        value=ew.mean, threshold=floor, step=step)
                    if d is not None:
                        out.append(d)
        if padding_ratio is not None:
            ew = self._pad
            ew.update(padding_ratio)
            if ew.n == cfg.warmup_steps:
                self._pad_baseline = ew.mean
            elif self._pad_baseline is not None and \
                    abs(ew.mean - self._pad_baseline) > cfg.padding_drift:
                d = self._detect(
                    "padding_drift",
                    f"padding ratio EWMA {ew.mean:.3f} drifted from "
                    f"its warmed baseline {self._pad_baseline:.3f}",
                    value=ew.mean, threshold=cfg.padding_drift, step=step)
                if d is not None:
                    out.append(d)
        return out

    def observe_mfu(self, mfu: Optional[float],
                    program: Optional[str] = None,
                    step: Optional[int] = None) -> List[Detection]:
        """Feed one sampled-fence MFU reading (the StepProfiler's
        roofline sample).  Same shape as the throughput detector: the
        EWMA tracks its own peak, and a collapse below
        ``mfu_floor_ratio`` x peak fires ``mfu_regression`` — the "same
        step rate, emptier device" signal a pure examples/sec detector
        cannot see (e.g. a padding blowup keeps steps/s flat while
        useful FLOPs crater)."""
        cfg = self.config
        out: List[Detection] = []
        if mfu is None:
            return out
        mfu = float(mfu)
        if not math.isfinite(mfu) or mfu <= 0:
            return out
        ew = self._mfu
        ew.update(mfu)
        if ew.n >= cfg.mfu_warmup:
            if ew.mean > self._mfu_peak:
                self._mfu_peak = ew.mean
            floor = cfg.mfu_floor_ratio * self._mfu_peak
            if self._mfu_peak > 0 and ew.mean < floor:
                prog = f" [{program}]" if program else ""
                d = self._detect(
                    "mfu_regression",
                    f"sampled MFU EWMA{prog} {ew.mean:.4f} fell below "
                    f"{cfg.mfu_floor_ratio:.0%} of peak "
                    f"{self._mfu_peak:.4f}",
                    value=ew.mean, threshold=floor, step=step)
                if d is not None:
                    out.append(d)
        return out

    # -- serving-side observers ---------------------------------------------
    def observe_request(self, seconds: Optional[float] = None,
                        shed: bool = False) -> List[Detection]:
        """Feed one serving request outcome (latency and/or a shed)."""
        cfg = self.config
        out: List[Detection] = []
        self._shed_ring.append(1 if shed else 0)
        if seconds is not None:
            self._latency.observe(seconds)
        if len(self._shed_ring) >= cfg.serving_min_samples:
            rate = sum(self._shed_ring) / len(self._shed_ring)
            if rate >= cfg.shed_rate_threshold:
                d = self._detect(
                    "shed_rate",
                    f"{rate:.0%} of the last {len(self._shed_ring)} "
                    "admissions were shed",
                    value=rate, threshold=cfg.shed_rate_threshold)
                if d is not None:
                    out.append(d)
        if cfg.p99_target_ms is not None and \
                len(self._latency) >= cfg.serving_min_samples:
            p99 = self._latency.quantile(0.99)
            if p99 is not None and p99 * 1e3 > cfg.p99_target_ms:
                d = self._detect(
                    "serving_p99",
                    f"p99 {p99 * 1e3:.1f} ms over target "
                    f"{cfg.p99_target_ms:.1f} ms",
                    value=p99 * 1e3, threshold=cfg.p99_target_ms)
                if d is not None:
                    out.append(d)
        return out

    def observe_generation(self, ttft_s: Optional[float] = None,
                           itl_s: Optional[float] = None
                           ) -> List[Detection]:
        """Feed one generation latency sample: time-to-first-token
        (request admitted → first token emitted, covers queue wait +
        prefill) and/or inter-token latency (one decode-step boundary to
        the next for a sequence).  Each has its own sliding-window p99
        detector so a decode tier drowning in prefills pages on TTFT
        while steady decode stays green — and vice versa."""
        cfg = self.config
        out: List[Detection] = []
        for window, sample, target, kind, label in (
                (self._ttft, ttft_s, cfg.ttft_p99_target_ms,
                 "generation_ttft_p99", "time-to-first-token"),
                (self._itl, itl_s, cfg.itl_p99_target_ms,
                 "generation_itl_p99", "inter-token latency")):
            if sample is None:
                continue
            window.observe(sample)
            if target is None or len(window) < cfg.serving_min_samples:
                continue
            p99 = window.quantile(0.99)
            if p99 is not None and p99 * 1e3 > target:
                d = self._detect(
                    kind,
                    f"generation {label} p99 {p99 * 1e3:.1f} ms over "
                    f"target {target:.1f} ms",
                    value=p99 * 1e3, threshold=target)
                if d is not None:
                    out.append(d)
        return out

    def note_slo_breach(self, detail: str, **fields: Any
                        ) -> Optional[Detection]:
        """Admission control reports an SLO-window breach edge."""
        return self._detect("slo_breach", detail, **fields)

    # -- state ---------------------------------------------------------------
    def should_stop(self) -> bool:
        """True once a detection occurred under ``stop_training=True`` —
        the fit loops (and :class:`HealthTermination`) poll this."""
        return self._stop

    def state(self) -> str:
        """``"ok"`` or ``"degraded"``: degraded while any sticky (NaN)
        detection exists or any detection is younger than the cooldown."""
        now = monotonic_s()
        degraded = False
        with self._lock:
            for det in self._detections:
                if det.kind in _STICKY_KINDS or \
                        now - det._mono < self.config.degraded_cooldown_s:
                    degraded = True
                    break
        reg = self._reg()
        if reg.enabled:
            # keep the gauge consistent with what /health reports: a
            # non-sticky detection aging past the cooldown must drop the
            # metric too, not page forever until an operator clear()
            reg.gauge("health_degraded",
                      "1 while the health monitor reports degraded"
                      ).set(1 if degraded else 0)
        return "degraded" if degraded else "ok"

    def reasons(self) -> List[str]:
        now = monotonic_s()
        with self._lock:
            return [f"{d.kind}: {d.reason}" for d in self._detections
                    if d.kind in _STICKY_KINDS
                    or now - d._mono < self.config.degraded_cooldown_s]

    def status(self) -> Dict[str, Any]:
        """The ``/health`` embed: state + active reasons + history."""
        with self._lock:
            dets = [d.to_dict() for d in self._detections]
        return {"state": self.state(), "reasons": self.reasons(),
                "detections": dets, "stopped": self._stop,
                "checkpoint_saves": self.checkpoint_saves,
                "steps_observed": self._steps}

    def clear(self) -> None:
        """Operator acknowledgement: drop all detections (including
        sticky ones) and re-arm; the statistical state is kept."""
        with self._lock:
            self._detections.clear()
            self._by_kind.clear()
            self._saved_kinds.clear()
            self._stop = False
        reg = self._reg()
        if reg.enabled:
            reg.gauge("health_degraded",
                      "1 while the health monitor reports degraded").set(0)


class HealthTermination:
    """Iteration-level termination condition bridging the monitor into
    the existing early-stopping terminations path (duck-typed to
    ``earlystopping.terminations.IterationTerminationCondition`` — same
    ``initialize()``/``terminate(last_score)`` contract)::

        conf = EarlyStoppingConfiguration(
            iteration_terminations=[HealthTermination(monitor)], ...)
    """

    def __init__(self, monitor: "HealthMonitor"):
        self.monitor = monitor

    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        self.monitor.observe_step(loss=last_score)
        return self.monitor.should_stop()


# process-global monitor: OFF (None) by default — health monitoring is
# an opt-in subsystem like tracing; installing one wires every fit loop,
# the serving admission path, and both /health endpoints at once.
_default: Optional[HealthMonitor] = None
_default_lock = threading.Lock()


def get_health_monitor() -> Optional[HealthMonitor]:
    return _default


def set_health_monitor(monitor: Optional[HealthMonitor]
                       ) -> Optional[HealthMonitor]:
    """Install the process-global monitor; returns the previous one
    (tests restore it in a finally block)."""
    global _default
    with _default_lock:
        prev, _default = _default, monitor
    return prev
