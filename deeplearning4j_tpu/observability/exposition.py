"""Prometheus text-format exposition (version 0.0.4) for a
:class:`~deeplearning4j_tpu.observability.registry.MetricsRegistry`.

Deterministic output: metric families sort by name, children by label
values, histogram buckets ascend, and the ``le`` label renders last —
so two renders of the same registry state are byte-identical (scrape
diffing and golden tests rely on this).
"""
from __future__ import annotations

import math
from typing import Dict, Optional

from .registry import MetricsRegistry

__all__ = ["render_text", "escape_label_value", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_label_value(value: str) -> str:
    """Escape per the exposition spec: backslash, double-quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    """Sample-value formatting: integral floats render as integers
    (Prometheus parses either; the short form keeps counters readable)."""
    if value != value:
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _labels_str(labels: Dict[str, str], le: Optional[str] = None) -> str:
    parts = [f'{k}="{escape_label_value(str(v))}"'
             for k, v in sorted(labels.items())]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def render_text(registry: MetricsRegistry) -> str:
    """Render every family in the registry as Prometheus exposition text."""
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for values, child in m.samples():
            labels = dict(zip(m.labelnames, values))
            if m.kind == "histogram":
                for bound, count in child.cumulative_buckets():
                    le = "+Inf" if bound == math.inf else _fmt(bound)
                    lines.append(f"{m.name}_bucket"
                                 f"{_labels_str(labels, le=le)} {count}")
                lines.append(f"{m.name}_sum{_labels_str(labels)} "
                             f"{_fmt(child.sum)}")
                lines.append(f"{m.name}_count{_labels_str(labels)} "
                             f"{child.count}")
            else:
                lines.append(f"{m.name}{_labels_str(labels)} "
                             f"{_fmt(child.value)}")
    return "\n".join(lines) + ("\n" if lines else "")
