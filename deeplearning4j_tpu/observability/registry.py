"""Dependency-free metrics registry: Counter / Gauge / Histogram with
label sets, thread-safe, with a process-global default registry plus
injectable instances.

Design constraints (the serving/training tiers both ride this):

- **stdlib only** — importable in minimal TPU-pod images;
- **off-by-default cheap** — a disabled registry turns every instrument
  write into a single attribute check and an early return, and no code
  path here ever touches a device value (callers hand us host floats);
- **bounded locking** — child creation takes the instrument lock once,
  after which the hot path is one per-child lock around plain float math
  (Python's ``+=`` on a float attribute is not atomic across threads).

The exposition formats (Prometheus text, JSON snapshot) live in
``exposition.py``; this module only owns the data model.
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "default_registry", "set_default_registry", "DEFAULT_BUCKETS"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Prometheus client-library default latency buckets (seconds).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("_metric", "_lock")

    def __init__(self, metric: "_Instrument"):
        self._metric = metric
        self._lock = threading.Lock()

    @property
    def _on(self) -> bool:
        return self._metric._registry._enabled


class _CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, metric):
        super().__init__(metric)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._on:
            return
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class _GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, metric):
        super().__init__(metric)
        self._value = 0.0

    def set(self, value: float) -> None:
        if not self._on:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._on:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class _HistogramChild(_Child):
    __slots__ = ("_bucket_counts", "_sum", "_count")

    def __init__(self, metric):
        super().__init__(metric)
        self._bucket_counts = [0] * len(metric.buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        if not self._on:
            return
        value = float(value)
        with self._lock:
            # non-cumulative per-bucket counts; exposition cumulates
            for i, bound in enumerate(self._metric.buckets):
                if value <= bound:
                    self._bucket_counts[i] += 1
                    break
            self._sum += value
            self._count += 1

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count), ...] ending at (+inf, count)."""
        out, acc = [], 0
        with self._lock:
            counts = list(self._bucket_counts)
            total = self._count
        for bound, c in zip(self._metric.buckets, counts):
            acc += c
            out.append((bound, acc))
        out.append((float("inf"), total))
        return out


class _Instrument:
    """Base for Counter/Gauge/Histogram: a named family of label children."""

    kind = "untyped"
    _child_cls = _Child

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: Sequence[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            # eagerly materialize the unlabeled series so zero-valued
            # metrics still appear in expositions
            self._children[()] = self._child_cls(self)

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(kw[ln] for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}") from e
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values,
                                                  self._child_cls(self))
        return child

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call .labels()")
        return self._children[()]

    def samples(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        """Deterministic (sorted by label values) child listing."""
        with self._lock:
            items = list(self._children.items())
        return sorted(items, key=lambda kv: kv[0])


class Counter(_Instrument):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Gauge(_Instrument):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().dec(amount)

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Histogram(_Instrument):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b != b or b == float("inf") for b in buckets):
            raise ValueError("bucket bounds must be finite (+Inf is implicit)")
        self.buckets = buckets
        super().__init__(registry, name, help, labelnames)

    def observe(self, value: float) -> None:
        self._unlabeled().observe(value)


class MetricsRegistry:
    """Thread-safe instrument store.  ``counter``/``gauge``/``histogram``
    are get-or-create: repeated calls with the same name return the same
    instrument (and raise on kind/label mismatch, which would otherwise
    corrupt the exposition)."""

    def __init__(self, enabled: bool = True):
        self._metrics: Dict[str, _Instrument] = {}
        self._lock = threading.Lock()
        self._enabled = enabled

    # -- lifecycle -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> "MetricsRegistry":
        self._enabled = True
        return self

    def disable(self) -> "MetricsRegistry":
        """No-op fast path: instrument writes become a bool check."""
        self._enabled = False
        return self

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- instrument factories ------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(self, name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}")
        if m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} registered with labels {m.labelnames}, "
                f"requested {tuple(labelnames)}")
        if "buckets" in kw:
            want = tuple(sorted(float(b) for b in kw["buckets"]))
            if want != m.buckets:
                raise ValueError(
                    f"histogram {name!r} registered with buckets "
                    f"{m.buckets}, requested {want}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # -- introspection -------------------------------------------------------
    def collect(self) -> List[_Instrument]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every time series (the /metrics?format=json
        payload and the offline-analysis sidecar of the Prometheus text).
        Histogram samples carry derived ``p50``/``p99`` summaries
        (nearest-rank over the bucket counts — an upper estimate bounded
        by bucket width) so dashboards consuming the JSON exposition
        don't re-implement quantile math; the Prometheus text format is
        unchanged."""
        from .quantiles import bucket_quantile
        out: Dict[str, Any] = {}
        for m in self.collect():
            samples = []
            for values, child in m.samples():
                labels = dict(zip(m.labelnames, values))
                if m.kind == "histogram":
                    cum = child.cumulative_buckets()
                    samples.append({
                        "labels": labels,
                        "buckets": [[b if b != float("inf") else "+Inf", c]
                                    for b, c in cum],
                        "sum": child.sum, "count": child.count,
                        "p50": bucket_quantile(cum, 0.50),
                        "p99": bucket_quantile(cum, 0.99)})
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[m.name] = {"type": m.kind, "help": m.help,
                          "samples": samples}
        return out


_default = MetricsRegistry(enabled=True)
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-global registry every built-in instrumentation point
    writes to unless handed an explicit instance."""
    return _default


def set_default_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry; returns the previous one (tests
    restore it in a finally block)."""
    global _default
    with _default_lock:
        prev, _default = _default, registry
    return prev
