"""StepProfiler: per-step time attribution with MFU, dispatch-depth,
and memory-watermark telemetry.

``/metrics`` says *how fast* a step was; nothing in the stack said
*where the time went* — host dispatch vs device compute vs ETL wait vs
listener/forensics bookkeeping — so every optimisation PR has had to
re-derive that split ad hoc.  The :class:`StepProfiler` attributes every
training step's wall time into named phases::

    etl_wait | h2d | dispatch | device | listener | forensics | checkpoint

and exports the result through every existing observability surface: a
bounded FlightRecorder ``profile`` channel (Chrome-trace dumpable,
served live at ``GET /debug/profile``), registry gauges
(``training_mfu{program}``, ``training_dispatch_depth``,
``device_live_bytes``), and the HealthMonitor's MFU-regression
detector.

Honesty model — the one thing this module must not lie about:

- The *device* slice can only be measured by materializing the step's
  result (``jax.block_until_ready``), which is exactly the per-step
  host sync the fit loops' async-dispatch design exists to avoid.  So
  the fence is SAMPLED: every ``sample_every``-th step pays one fence
  (counted in ``stepprof_fences_total``), all other steps stay fully
  async — zero extra syncs, the PR 16 host-sync sweep invariant.  On
  unsampled steps the device slice is ``None``, never an estimate.
- The **dispatch-depth gauge** counts async dispatches since the last
  materialization point the profiler can see (its own fences, plus
  materializations the caller reports via :meth:`materialized`): it
  makes pipelining visible — depth pinned at 0 means some hidden sync
  is serializing every step.
- **MFU** derives from the committed graftaudit card ``flops`` field
  (``tools/graftaudit/cards/``) — cards are the single source of truth
  for program FLOPs; no analytic formulas are duplicated here.  The
  peak-FLOP/s denominator comes from ``DL4J_TPU_PEAK_FLOPS`` or a
  per-chip table for known TPU kinds; with neither, achieved FLOP/s is
  still exported and the MFU gauge is withheld rather than faked.
- **Memory watermarks** sum live device bytes (``jax.live_arrays``) at
  fences and compare the observed peak against the AX008
  ``peak_live_bytes`` budget from ``tools/graftaudit/budgets.json``
  (``device_live_bytes_budget_ratio{program}``) — an approaching OOM
  pages before it happens.

Enablement: ``DL4J_TPU_STEPPROF`` (default on; the per-step cost is a
handful of ``perf_counter`` reads plus one buffered tuple append,
proven <2% by the ``profiler_overhead_ms`` paired-arm bench).
``DL4J_TPU_STEPPROF_SAMPLE`` sets the fence cadence (default 16);
``DL4J_TPU_STEPPROF_PROGRAM`` overrides the program label the fit
loops pass, mapping a run onto its canonical card/budget entry.

This module is the ONE place a fence inside a loop is legal — the
graftlint JX029 rule flags ``block_until_ready`` in loops everywhere
else in the package, because an unsampled fence in a hot loop is the
regression class the host-sync sweep removed.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional

from .clock import monotonic_s, wall_s
from .recorder import get_flight_recorder
from .registry import MetricsRegistry, default_registry

__all__ = ["StepProfiler", "step_profiler_for", "stepprof_enabled",
           "record_slices", "resolve_card_flops", "resolve_budget_bytes",
           "peak_device_flops", "live_device_bytes", "phase_summary",
           "chrome_trace", "dump_chrome_trace", "load_chrome_trace",
           "CHANNEL", "PHASES", "TRACE_FORMAT", "TRACE_PREFIX"]

CHANNEL = "profile"
PHASES = ("etl_wait", "h2d", "dispatch", "device", "listener",
          "forensics", "checkpoint")
TRACE_FORMAT = "dl4j-tpu-stepprof-trace-v1"
TRACE_PREFIX = "stepprof-"

#: serve/decode slice keys in their temporal order (Chrome-trace layout)
SLICE_KEYS = ("queue_wait_s", "batch_form_s", "execute_s")

# repo root when running from a checkout: profiler.py lives at
# <root>/deeplearning4j_tpu/observability/profiler.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# bf16 peak FLOP/s per chip for known TPU generations (the roofline
# denominator when DL4J_TPU_PEAK_FLOPS is not set); prefix-matched
# against device_kind, most specific first
_PEAK_FLOPS_BY_KIND = (
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 45e12),
)


def stepprof_enabled() -> bool:
    """Default on; ``DL4J_TPU_STEPPROF=0`` disables every hook."""
    return os.environ.get("DL4J_TPU_STEPPROF", "1") != "0"


def _default_sample_every() -> int:
    try:
        return max(1, int(os.environ.get("DL4J_TPU_STEPPROF_SAMPLE", "16")))
    except ValueError:
        return 16


# ---------------------------------------------------------------- cards
def _card_path(program: str) -> str:
    directory = os.environ.get("DL4J_TPU_CARDS_DIR") or os.path.join(
        _REPO_ROOT, "tools", "graftaudit", "cards")
    # mirrors tools/graftaudit/cards.card_filename (not imported: the
    # audit toolchain must stay optional at runtime)
    fname = re.sub(r"[^A-Za-z0-9_.-]+", "_", program) + ".json"
    return os.path.join(directory, fname)


def resolve_card_flops(program: str) -> Optional[float]:
    """FLOPs of one execution of ``program`` from its committed
    graftaudit card — the single source of truth for program cost; None
    when no card exists (installed package, un-audited program)."""
    try:
        with open(_card_path(program), "r", encoding="utf-8") as fh:
            flops = json.load(fh).get("flops")
        flops = float(flops)
        return flops if flops > 0 else None
    except (OSError, ValueError, TypeError):
        return None


def resolve_budget_bytes(program: str) -> Optional[int]:
    """The AX008 ``peak_live_bytes`` ceiling for ``program`` from
    ``tools/graftaudit/budgets.json`` (or ``DL4J_TPU_BUDGETS``)."""
    path = os.environ.get("DL4J_TPU_BUDGETS") or os.path.join(
        _REPO_ROOT, "tools", "graftaudit", "budgets.json")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            row = json.load(fh)["programs"][program]
        b = int(row["peak_live_bytes"])
        return b if b > 0 else None
    except (OSError, ValueError, KeyError, TypeError):
        return None


def peak_device_flops() -> Optional[float]:
    """Aggregate peak FLOP/s across local devices: ``DL4J_TPU_PEAK_FLOPS``
    (already aggregate) wins; else the per-chip table for known TPU
    kinds x device count; else None — MFU is withheld, never faked."""
    env = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if env:
        try:
            peak = float(env)
            return peak if peak > 0 else None
        except ValueError:
            return None
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return None
    kind = str(getattr(devices[0], "device_kind", "") or "")
    for prefix, peak in _PEAK_FLOPS_BY_KIND:
        if kind.startswith(prefix):
            return peak * len(devices)
    return None


def live_device_bytes() -> Optional[int]:
    """Sum of live device-array bytes (the observed-watermark sample
    taken at fences); None when the runtime can't say."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:
        return None
    total = 0
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:
            pass   # deleted/donated buffers race the walk; skip them
    return total


class StepProfiler:
    """Per-step phase attribution for one fit/serve loop.

    Hot-path protocol (the fit loops drive it; every call is a couple of
    ``perf_counter`` reads and float math — no allocation, no locks, no
    device access on unsampled steps)::

        prof.begin(t_step, etl_s)      # loop's existing step-start read
        prof.mark("h2d", dt)           # inner slices, from _fit_one
        prof.mark("listener", dt)
        prof.dispatched(loss)          # async dispatch returned; maybe
                                       #   fence (sampled): device slice,
                                       #   live bytes, MFU
        prof.lap("forensics")          # bookkeeping laps
        prof.lap("checkpoint")
        prof.end(iteration, compile_step)

    Step records buffer as raw tuples and drain into the FlightRecorder
    ``profile`` channel every ``FLUSH_EVERY`` steps (the
    ``_StepForensics`` amortization pattern); ``flush()`` in the loop's
    ``finally`` guarantees no step is lost to an exception."""

    FLUSH_EVERY = 16
    __slots__ = ("program", "enabled", "sample_every", "ring", "fences",
                 "steps", "dispatch_depth", "max_depth",
                 "live_bytes_watermark", "card_flops", "budget_bytes",
                 "peak_flops", "last_mfu", "last_achieved_flops",
                 "_registry", "_monitor", "_wall0", "_buf", "_t0", "_last",
                 "_etl", "_h2d", "_listener", "_dispatch", "_device",
                 "_forensics", "_checkpoint", "_sampled", "_drained_wait",
                 "_live", "_ratio", "_mfu", "_ach")

    def __init__(self, program: str = "train_step", *,
                 sample_every: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 recorder=None, monitor=None):
        self.program = program
        self.enabled = True
        self.sample_every = max(1, int(sample_every)) \
            if sample_every is not None else _default_sample_every()
        rec = recorder if recorder is not None else get_flight_recorder()
        self.ring = rec.channel(CHANNEL) \
            if (rec is not None and rec.enabled) else None
        self._registry = registry
        self._monitor = monitor
        # cold, once per fit: committed card/budget lookups + roofline
        self.card_flops = resolve_card_flops(program)
        self.budget_bytes = resolve_budget_bytes(program)
        self.peak_flops = peak_device_flops() if self.card_flops else None
        self.fences = 0
        self.steps = 0
        self.dispatch_depth = 0
        self.max_depth = 0
        self.live_bytes_watermark = 0
        self.last_mfu: Optional[float] = None
        self.last_achieved_flops: Optional[float] = None
        # record timestamps derive from the monotonic reads the loop
        # already takes (the _StepForensics wall0 trick)
        self._wall0 = wall_s() - monotonic_s()
        self._buf: list = []
        self._t0 = self._last = 0.0
        self._etl = self._h2d = self._listener = 0.0
        self._dispatch = self._forensics = self._checkpoint = 0.0
        self._device: Optional[float] = None
        self._sampled = False
        self._drained_wait = 0.0
        self._live: Optional[int] = None
        self._ratio: Optional[float] = None
        self._mfu: Optional[float] = None
        self._ach: Optional[float] = None

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else default_registry()

    # ------------------------------------------------------ step protocol
    def begin(self, t0: float, etl_s: float = 0.0) -> None:
        """Open a step at the loop's own step-start monotonic read;
        ``etl_s`` is the already-measured time blocked on the pipeline
        *before* ``t0`` (the step record's window starts at etl start)."""
        self._t0 = self._last = t0
        self._etl = etl_s if etl_s > 0.0 else 0.0
        self._h2d = self._listener = 0.0
        self._dispatch = self._forensics = self._checkpoint = 0.0
        self._device = None
        self._sampled = False
        self._drained_wait = 0.0

    def mark(self, phase: str, seconds: float) -> None:
        """Credit an inner slice measured by the step body (h2d device
        placement, the listener loop) — subtracted from the enclosing
        dispatch window so nothing is double-counted."""
        if phase == "h2d":
            self._h2d += seconds
        elif phase == "listener":
            self._listener += seconds

    def dispatched(self, handle=None, window=None) -> None:
        """The async step dispatch returned.  Every ``sample_every``-th
        step additionally fences on ``handle`` to measure the device
        slice honestly (the ONLY profiler-added sync; counted).

        ``window``: the fit loop's bounded :class:`~..nn.dispatch.
        DispatchWindow` (or None).  A sampled fence first drains it,
        attributing each drained step's device slice individually by
        completion spacing — without this, the device time of steps still
        in flight would be billed to the fenced step's slice."""
        now = monotonic_s()
        self._dispatch = now - self._last - self._h2d - self._listener
        self._last = now
        self.steps += 1
        depth = self.dispatch_depth + 1
        self.dispatch_depth = depth
        if depth > self.max_depth:
            self.max_depth = depth
        if handle is not None and self.steps % self.sample_every == 0:
            self._fence(handle, now, window)

    def drained(self, k: int = 1) -> None:
        """The dispatch window materialized ``k`` in-flight steps: the
        pipeline shortened — keep the depth gauge tracking real window
        occupancy (steady state: ``max_depth`` == configured depth)."""
        d = self.dispatch_depth - k
        self.dispatch_depth = d if d > 0 else 0

    def lap(self, phase: str) -> None:
        """Close a bookkeeping slice (forensics / checkpoint) at now."""
        now = monotonic_s()
        if phase == "forensics":
            self._forensics = now - self._last
        elif phase == "checkpoint":
            self._checkpoint = now - self._last
        self._last = now

    def end(self, iteration: int, compile_step: bool = False) -> None:
        """Seal the step record (wall = etl + everything since begin).
        A LIST, not a tuple: a later pipeline-aware fence may patch the
        device slice in once the step's in-flight token drains."""
        # the fence's wait on EARLIER steps' in-flight tokens is billed
        # to those steps' records (_patch_device), so it is excluded from
        # this step's wall — the coverage contract (phase sum == wall on
        # sampled steps) holds at every dispatch depth, nothing is
        # counted twice
        wall = self._etl + (monotonic_s() - self._t0) - self._drained_wait
        self._buf.append([
            self._wall0 + self._t0 - self._etl, iteration, wall,
            self._etl, self._h2d, self._dispatch, self._device,
            self._listener, self._forensics, self._checkpoint,
            self._sampled, compile_step, self.dispatch_depth,
            self._live, self._ratio, self._mfu, self._ach])
        if len(self._buf) >= self.FLUSH_EVERY:
            self.flush()

    def materialized(self) -> None:
        """The caller just forced a host sync outside the profiler's own
        fences (epoch-end score float, a monitor's same-step check): the
        dispatch pipeline is drained — reset the depth baseline."""
        self.dispatch_depth = 0

    # ------------------------------------------------- fence (cold, 1/N)
    def _patch_device(self, iteration: int, seconds: float) -> None:
        """Attribute a drained in-flight step's device slice to ITS OWN
        buffered record (found by iteration; the record may already have
        flushed — a miss just leaves that slice unattributed, never
        mis-billed).  A fence-measured device value is never overwritten."""
        for rec in reversed(self._buf):
            if rec[1] == iteration:
                if rec[6] is None:
                    rec[6] = seconds
                return

    def _fence(self, handle, t_disp: float, window=None) -> None:
        import jax
        # pipeline-aware: drain the bounded window FIRST, attributing each
        # drained step's device slice by completion spacing, so the fenced
        # step's slice below is its own marginal device time — not the
        # queued tail of every step still in flight
        t_prev = t_disp
        if window is not None and len(window):
            for iteration, t_done in window.drain_timed():
                self._patch_device(iteration, t_done - t_prev)
                t_prev = t_done
            self._drained_wait = t_prev - t_disp
        jax.block_until_ready(handle)
        now = monotonic_s()
        device = now - t_prev
        self._device = device
        self._last = now
        self._sampled = True
        self.fences += 1
        if window is None:
            # no bounded window feeding drained(): the fence is the only
            # materialization point, so it resets the occupancy itself
            self.dispatch_depth = 0
        # with a window, the books already balance: the drain above
        # retired every EARLIER step's slot via drained(), and the
        # fenced step's own slot — counted by its dispatched() — is
        # retired by its own pop when the loop pushes its token.  A
        # hard reset here would make that pop a double decrement and
        # pin the steady-state gauge at depth-1 instead of the
        # configured depth.
        live = live_device_bytes()
        self._live = live
        if live is not None and live > self.live_bytes_watermark:
            self.live_bytes_watermark = live
        ratio = None
        if self.budget_bytes and self.live_bytes_watermark:
            ratio = self.live_bytes_watermark / self.budget_bytes
        self._ratio = ratio
        achieved = mfu = None
        if self.card_flops and device > 0:
            achieved = self.card_flops / device
            self.last_achieved_flops = achieved
            if self.peak_flops:
                mfu = achieved / self.peak_flops
                self.last_mfu = mfu
        self._ach, self._mfu = achieved, mfu
        if mfu is not None:
            mon = self._monitor
            if mon is None:
                from .health import get_health_monitor
                mon = get_health_monitor()
            if mon is not None:
                mon.observe_mfu(mfu, program=self.program, step=self.steps)
        reg = self._reg()
        if reg.enabled:
            p = self.program
            reg.counter("stepprof_fences_total",
                        "Sampled block_until_ready fences taken by the "
                        "step profiler", ("program",)).labels(p).inc()
            reg.gauge("training_dispatch_depth",
                      "Async dispatches in flight between materialization "
                      "points (max over the last sample window)"
                      ).set(self.max_depth)
            self.max_depth = 0
            if achieved is not None:
                reg.gauge("training_achieved_flops",
                          "Achieved FLOP/s of the sampled device slice "
                          "(card flops / fenced device time)",
                          ("program",)).labels(p).set(achieved)
            if mfu is not None:
                reg.gauge("training_mfu",
                          "Model FLOP/s utilization: achieved over peak "
                          "device FLOP/s", ("program",)).labels(p).set(mfu)
            if live is not None:
                reg.gauge("device_live_bytes",
                          "Live device bytes sampled at the last profiler "
                          "fence").set(live)
            if ratio is not None:
                reg.gauge("device_live_bytes_budget_ratio",
                          "Observed live-bytes watermark over the AX008 "
                          "peak_live_bytes budget",
                          ("program",)).labels(p).set(ratio)

    # ------------------------------------------------------- flush (cold)
    def flush(self) -> None:
        """Drain buffered steps into the recorder's ``profile`` ring."""
        buf = self._buf
        if not buf:
            return
        self._buf = []
        ring = self.ring
        if ring is None:
            return
        prog = self.program
        for (ts, it, wall, etl, h2d, disp, dev, lst, fore, ckpt,
             sampled, comp, depth, live, ratio, mfu, ach) in buf:
            rec = {"ts": ts, "type": "step", "program": prog,
                   "iteration": it, "wall_s": round(wall, 7),
                   "sampled": sampled, "compile": comp, "depth": depth,
                   # a device slice on an UNSAMPLED record came from a
                   # later fence draining this step's in-flight token —
                   # honest timing, but attributed after the fact
                   **({"drained": True}
                      if (not sampled and dev is not None) else {}),
                   "phases": {
                       "etl_wait": round(etl, 7),
                       "h2d": round(h2d, 7),
                       "dispatch": round(disp, 7),
                       "device": None if dev is None else round(dev, 7),
                       "listener": round(lst, 7),
                       "forensics": round(fore, 7),
                       "checkpoint": round(ckpt, 7)}}
            if live is not None:
                rec["live_bytes"] = live
            if ratio is not None:
                rec["budget_ratio"] = round(ratio, 4)
            if mfu is not None:
                rec["mfu"] = mfu
            if ach is not None:
                rec["achieved_flops"] = ach
            ring.append(rec)


def step_profiler_for(program: str, **kwargs) -> Optional[StepProfiler]:
    """The fit loops' entry point: a fresh profiler, or None when
    ``DL4J_TPU_STEPPROF=0`` — and never an exception, because telemetry
    must not break training.  ``DL4J_TPU_STEPPROF_PROGRAM`` overrides
    the label (mapping a run onto its canonical card/budget entry)."""
    if not stepprof_enabled():
        return None
    program = os.environ.get("DL4J_TPU_STEPPROF_PROGRAM", program)
    try:
        return StepProfiler(program, **kwargs)
    except Exception:
        return None


def record_slices(kind: str, *, recorder=None, **fields: Any) -> None:
    """Serve/decode-side contribution to the ``profile`` channel: one
    record per batch/step with its ``*_s`` slices (``queue_wait_s``,
    ``batch_form_s``, ``execute_s``).  A cheap guarded single
    ``record()`` — the serving loops call this once per *batch*, not
    per request."""
    if not stepprof_enabled():
        return
    rec = recorder if recorder is not None else get_flight_recorder()
    if rec is None or not rec.enabled:
        return
    rec.record(CHANNEL, kind, **fields)


# ------------------------------------------------------------- summaries
def phase_summary(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate ``profile``-channel step records into the text-table /
    ``/debug/profile`` summary: mean seconds + share per phase over
    steady (non-compile) steps, and the sampled-step coverage (phase
    sum over measured wall — the honesty check)."""
    steps = [r for r in records if r.get("type") == "step"
             and not r.get("compile")]
    out: Dict[str, Any] = {"steps": len(steps)}
    if not steps:
        return out
    wall = sum(r.get("wall_s", 0.0) for r in steps)
    phases: Dict[str, float] = {}
    for r in steps:
        for name, v in (r.get("phases") or {}).items():
            if v:
                phases[name] = phases.get(name, 0.0) + v
    n = len(steps)
    out["mean_wall_s"] = wall / n
    out["mean_phase_s"] = {k: phases.get(k, 0.0) / n for k in PHASES}
    out["phase_share"] = {k: (phases.get(k, 0.0) / wall if wall else 0.0)
                          for k in PHASES}
    sampled = [r for r in steps if r.get("sampled")]
    out["sampled_steps"] = len(sampled)
    if sampled:
        cov = [sum(v for v in (r.get("phases") or {}).values() if v)
               / r["wall_s"] for r in sampled if r.get("wall_s")]
        if cov:
            out["sampled_coverage"] = sum(cov) / len(cov)
        mfus = [r["mfu"] for r in sampled if r.get("mfu") is not None]
        if mfus:
            out["mean_mfu"] = sum(mfus) / len(mfus)
        ratios = [r["budget_ratio"] for r in sampled
                  if r.get("budget_ratio") is not None]
        if ratios:
            out["max_budget_ratio"] = max(ratios)
    return out


# ----------------------------------------------------------- Chrome trace
_TRACK_HOST, _TRACK_DEVICE = 1, 2


def chrome_trace(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Build a Chrome-trace (catapult JSON, ``chrome://tracing`` /
    Perfetto loadable) document from ``profile``-channel records.  Train
    steps lay their host phases sequentially on a host track with the
    sampled device slice on its own track (it genuinely overlaps
    nothing — the fence serialized it); serve/decode records place
    their ``*_s`` slices on per-subsystem tracks."""
    events: List[Dict[str, Any]] = []
    pids: Dict[int, str] = {}
    for r in records:
        kind = r.get("type")
        ts = float(r.get("ts", 0.0)) * 1e6   # catapult wants microseconds
        if kind == "step":
            pid = 1
            pids[pid] = f"train [{r.get('program', 'train_step')}]"
            args = {"iteration": r.get("iteration"),
                    "depth": r.get("depth"),
                    "sampled": bool(r.get("sampled"))}
            for opt in ("mfu", "live_bytes", "budget_ratio"):
                if r.get(opt) is not None:
                    args[opt] = r[opt]
            cursor = ts
            ph = r.get("phases") or {}
            for name in ("etl_wait", "h2d", "dispatch"):
                d = ph.get(name) or 0.0
                if d > 0:
                    events.append({"name": name, "cat": "train", "ph": "X",
                                   "pid": pid, "tid": _TRACK_HOST,
                                   "ts": cursor, "dur": d * 1e6,
                                   "args": args})
                cursor += d * 1e6
            dev = ph.get("device")
            if dev:
                events.append({"name": "device", "cat": "train", "ph": "X",
                               "pid": pid, "tid": _TRACK_DEVICE,
                               "ts": cursor, "dur": dev * 1e6,
                               "args": args})
                cursor += dev * 1e6
            for name in ("listener", "forensics", "checkpoint"):
                d = ph.get(name) or 0.0
                if d > 0:
                    events.append({"name": name, "cat": "train", "ph": "X",
                                   "pid": pid, "tid": _TRACK_HOST,
                                   "ts": cursor, "dur": d * 1e6,
                                   "args": args})
                cursor += d * 1e6
        elif kind in ("serve", "decode", "prefill"):
            pid = 2 if kind == "serve" else 3
            pids[pid] = "serving" if kind == "serve" else "generation"
            cursor = ts
            for key in SLICE_KEYS:
                d = r.get(key) or 0.0
                if d > 0:
                    events.append({"name": f"{kind}:{key[:-2]}",
                                   "cat": kind, "ph": "X", "pid": pid,
                                   "tid": _TRACK_HOST, "ts": cursor,
                                   "dur": d * 1e6})
                cursor += d * 1e6
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name}} for pid, name in sorted(pids.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms",
            "otherData": {"format": TRACE_FORMAT, "records": len(records)}}


def _seal_trace(doc: Dict[str, Any]) -> bytes:
    """Stamp a sha256 over the canonical traceEvents into the document
    (extra top-level keys are legal catapult metadata, so the artifact
    stays chrome://tracing-loadable AND checksum-verifiable)."""
    canonical = json.dumps(doc["traceEvents"], sort_keys=True,
                           separators=(",", ":")).encode("utf-8")
    doc = dict(doc)
    doc["sha256"] = hashlib.sha256(canonical).hexdigest()
    return json.dumps(doc).encode("utf-8")


def dump_chrome_trace(directory: Optional[str] = None,
                      records: Optional[List[Dict[str, Any]]] = None,
                      recorder=None) -> str:
    """Commit the current ``profile`` window as an atomic checksummed
    Chrome-trace artifact; returns the path written."""
    rec = recorder if recorder is not None else get_flight_recorder()
    if records is None:
        records = rec.channel(CHANNEL).items() if rec is not None else []
    if directory is None and rec is not None:
        directory = rec._resolve_directory(None)
    directory = directory or os.getcwd()
    blob = _seal_trace(chrome_trace(records))
    path = os.path.join(
        directory, f"{TRACE_PREFIX}{os.getpid()}-{int(wall_s())}.json")
    from ..faulttolerance.atomic import atomic_write_bytes
    os.makedirs(directory, exist_ok=True)
    atomic_write_bytes(path, blob)
    return path


def load_chrome_trace(path: str, verify: bool = True) -> Dict[str, Any]:
    """Read a stepprof Chrome-trace artifact; with ``verify`` (default)
    the embedded checksum is recomputed over the canonical traceEvents —
    truncation or bit rot raises ``ValueError``, never loads quietly."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if "traceEvents" not in doc or "sha256" not in doc:
        raise ValueError(f"{path}: not a stepprof trace artifact")
    if verify:
        canonical = json.dumps(doc["traceEvents"], sort_keys=True,
                               separators=(",", ":")).encode("utf-8")
        want, got = doc["sha256"], hashlib.sha256(canonical).hexdigest()
        if want != got:
            raise ValueError(
                f"{path}: checksum mismatch (artifact corrupt): recorded "
                f"{want[:12]}…, recomputed {got[:12]}…")
    return doc
