"""Flight recorder: crash-time forensics for training and serving.

``/metrics`` answers "what is the state *now*"; when a run dies — an
unhandled fit exception, a SIGTERM preemption, a watchdog eviction, a
serving SLO breach — *now* is already gone.  The
:class:`FlightRecorder` keeps the recent past in bounded, thread-safe
ring buffers (per-subsystem **channels** of structured events, the most
recent tracer **spans**, and periodic **metric snapshots**) and, when
something goes wrong, ``dump()`` commits the whole window to disk as an
atomic, checksummed JSON artifact through the same temp-then-rename
path checkpoints use (``faulttolerance/atomic.py``) — the artifact that
explains the 3am incident is on disk before the process is.

Cost model: recording is a dict build plus a deque append under a
per-ring lock (no device values, no clocks beyond one wall read), so
the recorder is ON by default like the metrics registry; a disabled
recorder reduces ``record()`` to one bool check.  Dumping is the cold
path and may import/IO freely.

Channel conventions (callers may invent more):

- ``train``   — per-step loss/grad-norm/throughput records, fit faults
- ``serving`` — batch dispatches, shed/SLO events, predict failures
- ``cluster`` — membership: heartbeats, evictions, chaos faults
- ``broker``  — messaging-layer incidents
- ``health``  — :class:`~.health.HealthMonitor` detections
- ``events``  — mirror of :func:`~.events.emit_event`

Artifact layout (see README "Observability")::

    {"sha256": <hex over canonical payload>,
     "payload": {"format": "dl4j-tpu-flightrec-v1", "reason": ...,
                 "ts": ..., "pid": ..., "seq": ...,
                 "channels": {name: [records...]},
                 "spans": [...], "metric_snapshots": [...],
                 "dropped": {name: n}}}

``load_dump`` re-canonicalizes the payload and verifies the checksum,
so a truncated or bit-flipped artifact is detected, never trusted.
"""
from __future__ import annotations

import collections
import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Sequence

from .clock import monotonic_s, wall_s
from .registry import MetricsRegistry, default_registry

__all__ = ["FlightRecorder", "get_flight_recorder", "set_flight_recorder",
           "load_dump", "FORMAT", "DUMP_PREFIX"]

FORMAT = "dl4j-tpu-flightrec-v1"
DUMP_PREFIX = "flightrec-"
_REASON_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


class _Ring:
    """Bounded deque of JSON-able records; appends are O(1) under one
    lock, eviction counts are kept so a dump can say what it lost."""

    __slots__ = ("_d", "_lock", "dropped")

    def __init__(self, capacity: int):
        self._d: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.dropped = 0

    def append(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._d) == self._d.maxlen:
                self.dropped += 1
            self._d.append(record)

    def items(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._d)

    def __len__(self) -> int:
        return len(self._d)


class FlightRecorder:
    """Bounded in-memory forensics window with atomic checksummed dumps.

    ``capacity``: records kept per channel; ``span_capacity`` /
    ``snapshot_capacity`` bound the span and metric-snapshot rings.
    ``directory``: where auto-triggered dumps land (fallback:
    ``DL4J_TPU_FLIGHTREC_DIR``); triggers with their own better location
    (the preemption checkpoint store, a job dir) pass it explicitly.
    ``min_dump_interval_s`` rate-limits :meth:`maybe_dump` per reason so
    a repeating fault (an SLO breach probed every second) cannot spam
    the disk — the first dump of a burst is the forensically useful one.
    ``min_snapshot_interval_s`` floors the cadence of periodic metric
    snapshots: a full registry snapshot costs ~1ms, so a fast step loop
    calling :meth:`snapshot_metrics` every N steps would both tax the
    step and compress the 16-slot ring into a couple of seconds of
    history — the time floor keeps the amortized cost ~0 and stretches
    the ring into minutes of trajectory (``dump()`` still captures the
    final state unconditionally).
    """

    def __init__(self, capacity: int = 256, span_capacity: int = 256,
                 snapshot_capacity: int = 16,
                 directory: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 enabled: bool = True,
                 min_dump_interval_s: float = 30.0,
                 min_snapshot_interval_s: float = 10.0):
        self.capacity = int(capacity)
        self.directory = directory
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.min_snapshot_interval_s = float(min_snapshot_interval_s)
        # the throttle clock starts at construction: the first periodic
        # snapshot also waits out the interval (a trajectory needs time
        # to exist; dump() force-captures the final state regardless)
        self._last_snap_mono: float = monotonic_s()
        self._registry = registry
        self._enabled = bool(enabled)
        self._channels: Dict[str, _Ring] = {}
        self._chan_lock = threading.Lock()
        self._spans = _Ring(span_capacity)
        self._snapshots = _Ring(snapshot_capacity)
        self._dump_lock = threading.Lock()
        self._last_dump_mono: Dict[str, float] = {}
        self._seq = 0
        self.dumps: List[str] = []     # paths written by this recorder

    # -- state ---------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> "FlightRecorder":
        self._enabled = True
        return self

    def disable(self) -> "FlightRecorder":
        self._enabled = False
        return self

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else default_registry()

    def channel(self, name: str) -> _Ring:
        ring = self._channels.get(name)
        if ring is None:
            with self._chan_lock:
                ring = self._channels.setdefault(name, _Ring(self.capacity))
        return ring

    # -- recording (hot path) ------------------------------------------------
    def record(self, channel: str, type: str, **fields: Any) -> None:
        """Append one structured record to ``channel``'s ring.  The
        kwargs dict is fresh per call, so it IS the record — stamping it
        in place keeps the hot path at one dict build, one dict lookup,
        and one locked append.  A caller-supplied ``ts`` is kept (batched
        feeders record when the event *happened*, not when it drained)."""
        if not self._enabled:
            return
        if "ts" not in fields:
            fields["ts"] = wall_s()
        fields["type"] = type
        ring = self._channels.get(channel)
        if ring is None:
            ring = self.channel(channel)
        ring.append(fields)

    def record_span(self, span) -> None:
        """Append a finished tracer span (``Span`` or its dict form)."""
        if not self._enabled:
            return
        self._spans.append(span.to_dict() if hasattr(span, "to_dict")
                           else dict(span))

    def snapshot_metrics(self, registry: Optional[MetricsRegistry] = None,
                         force: bool = False) -> None:
        """Capture one full registry snapshot into the snapshot ring —
        call periodically (the training loop does, every N steps) so a
        dump carries the metric *trajectory*, not just the final value.
        Periodic calls are floored at ``min_snapshot_interval_s`` apart
        (an explicit registry or ``force=True`` bypasses the floor — a
        caller naming the registry wants *that* snapshot now)."""
        if not self._enabled:
            return
        now = monotonic_s()
        if not force and registry is None and \
                now - self._last_snap_mono < self.min_snapshot_interval_s:
            return
        self._last_snap_mono = now
        reg = registry if registry is not None else self._reg()
        self._snapshots.append({"ts": wall_s(), "metrics": reg.snapshot()})

    # -- inspection ----------------------------------------------------------
    def view(self) -> Dict[str, Any]:
        """JSON-able live view (the ``/debug/flightrecorder`` payload)."""
        return {
            "enabled": self._enabled,
            "capacity": self.capacity,
            "directory": self._resolve_directory(None),
            "channels": {n: r.items() for n, r in
                         sorted(self._channels.items())},
            "spans": self._spans.items(),
            "metric_snapshots": self._snapshots.items(),
            "dropped": {n: r.dropped for n, r in
                        sorted(self._channels.items()) if r.dropped},
            "dumps": list(self.dumps),
        }

    # -- dumping (cold path) -------------------------------------------------
    def _resolve_directory(self, directory: Optional[str]) -> Optional[str]:
        return (directory or self.directory
                or os.environ.get("DL4J_TPU_FLIGHTREC_DIR") or None)

    def dump(self, reason: str, directory: Optional[str] = None,
             channels: Optional[Sequence[str]] = None,
             snapshot: bool = True) -> Optional[str]:
        """Commit the current window to an atomic, checksummed artifact;
        returns the path (None when the recorder is disabled).  With no
        resolvable directory the artifact lands in the cwd — an explicit
        ``dump()`` call means the caller wants a file; the automatic
        triggers go through :meth:`maybe_dump`, which never guesses."""
        if not self._enabled:
            return None
        if snapshot:
            try:
                self.snapshot_metrics(force=True)
            except Exception:
                pass   # a broken snapshot must not block crash forensics
        directory = self._resolve_directory(directory) or os.getcwd()
        with self._dump_lock:
            self._seq += 1
            seq = self._seq
        names = (sorted(self._channels) if channels is None
                 else [c for c in channels if c in self._channels])
        payload = {
            "format": FORMAT,
            "reason": str(reason),
            "ts": wall_s(),
            "pid": os.getpid(),
            "seq": seq,
            "channels": {n: self._channels[n].items() for n in names},
            "spans": self._spans.items(),
            "metric_snapshots": self._snapshots.items(),
            "dropped": {n: self._channels[n].dropped for n in names
                        if self._channels[n].dropped},
        }
        blob = _seal(payload)
        slug = _REASON_RE.sub("-", str(reason))[:48] or "dump"
        path = os.path.join(
            directory, f"{DUMP_PREFIX}{slug}-{os.getpid()}-{seq:04d}.json")
        # lazy import: atomic.py is stdlib-only, but routing through the
        # faulttolerance package at module import time would cycle
        from ..faulttolerance.atomic import atomic_write_bytes
        os.makedirs(directory, exist_ok=True)
        atomic_write_bytes(path, blob)
        self.dumps.append(path)
        self._last_dump_mono[str(reason)] = monotonic_s()
        reg = self._reg()
        if reg.enabled:
            reg.counter("flightrecorder_dumps_total",
                        "Flight-recorder artifacts committed to disk",
                        ("reason",)).labels(slug).inc()
        return path

    def maybe_dump(self, reason: str, directory: Optional[str] = None,
                   channels: Optional[Sequence[str]] = None
                   ) -> Optional[str]:
        """The automatic-trigger entry point: dump unless (a) no
        directory is configured anywhere — an auto trigger must never
        litter the cwd — or (b) the same reason dumped less than
        ``min_dump_interval_s`` ago.  Never raises: a failed forensics
        write must not turn an incident into a second incident."""
        if not self._enabled:
            return None
        if self._resolve_directory(directory) is None:
            return None
        last = self._last_dump_mono.get(str(reason))
        if last is not None and \
                monotonic_s() - last < self.min_dump_interval_s:
            return None
        try:
            return self.dump(reason, directory=directory, channels=channels)
        except Exception:
            return None


def _seal(payload: Dict[str, Any]) -> bytes:
    """Wrap ``payload`` with a sha256 over its canonical JSON form."""
    canonical = json.dumps(payload, sort_keys=True, default=str,
                           separators=(",", ":")).encode("utf-8")
    sha = hashlib.sha256(canonical).hexdigest()
    return json.dumps({"sha256": sha, "payload": payload},
                      default=str).encode("utf-8")


def load_dump(path: str, verify: bool = True) -> Dict[str, Any]:
    """Read a flight-recorder artifact and return its payload.  With
    ``verify`` (default) the embedded checksum is recomputed over the
    canonical payload; a mismatch — truncation, bit rot, a hand-edited
    artifact — raises ``ValueError`` rather than returning bad forensics."""
    with open(path, "r", encoding="utf-8") as f:
        artifact = json.load(f)
    payload = artifact.get("payload")
    if payload is None or "sha256" not in artifact:
        raise ValueError(f"{path}: not a flight-recorder artifact")
    if verify:
        canonical = json.dumps(payload, sort_keys=True, default=str,
                               separators=(",", ":")).encode("utf-8")
        want, got = artifact["sha256"], hashlib.sha256(canonical).hexdigest()
        if want != got:
            raise ValueError(
                f"{path}: checksum mismatch (artifact corrupt): "
                f"recorded {want[:12]}…, recomputed {got[:12]}…")
    return payload


# process-global recorder: ON by default (bounded deque appends are in
# the metrics-registry cost class); DL4J_TPU_FLIGHTREC=0 disables, and
# DL4J_TPU_FLIGHTREC_DIR gives auto-triggered dumps a home without code
# changes (the knob production pods flip)
_default: Optional[FlightRecorder] = FlightRecorder(
    enabled=os.environ.get("DL4J_TPU_FLIGHTREC", "1") != "0")
_default_lock = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The process-global recorder every built-in trigger point uses
    unless handed an explicit instance; None disables them all."""
    return _default


def set_flight_recorder(recorder: Optional[FlightRecorder]
                        ) -> Optional[FlightRecorder]:
    """Swap the process-global recorder; returns the previous one (tests
    restore it in a finally block)."""
    global _default
    with _default_lock:
        prev, _default = _default, recorder
    return prev
