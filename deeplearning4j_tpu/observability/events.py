"""Structured JSONL event log for offline analysis.

One JSON object per line: ``{"ts": <wall seconds>, "type": <str>, ...}``.
Writers are thread-safe (one lock around the write; lines stay atomic)
and the module-level sink is a no-op until :func:`configure_event_log`
points it somewhere — the same off-by-default posture as the registry
and tracer.  Consumers are anything that reads JSONL: pandas, jq, or
``tools/trace_categorize.py``-style scripts.

**Rotation**: long runs emit events forever, so an unbounded JSONL file
is a disk-filler.  With ``max_bytes`` set, a write that pushes the
active file past the limit rotates it: ``events.jsonl`` becomes
``events.jsonl.1`` (existing ``.1`` shifts to ``.2``, and so on up to
``max_files`` total segments — the oldest falls off the end).  Every
shift is one ``os.replace`` (atomic on POSIX), so a crash mid-rotation
leaves whole segments, never spliced ones.  :meth:`EventLog.read`
iterates records across all surviving segments oldest-first, so
consumers see one continuous stream regardless of how many times the
log rotated underneath them.

Every :func:`emit_event` also lands in the process flight recorder's
``events`` ring (when one is installed) — the JSONL file is the durable
archive, the ring is the crash-time window a dump preserves.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

from .clock import wall_s
from .recorder import get_flight_recorder

__all__ = ["EventLog", "configure_event_log", "get_event_log", "emit_event"]


class EventLog:
    """Append-only JSONL writer with optional size-based rotation.

    ``max_bytes``: rotate when the active file reaches this size (None =
    never, the historical behavior).  ``max_files``: total segments kept
    including the active one (minimum 1; 1 means rotation truncates)."""

    def __init__(self, path: str, append: bool = True,
                 max_bytes: Optional[int] = None, max_files: int = 5):
        self.path = str(path)
        self.max_bytes = None if not max_bytes else int(max_bytes)
        self.max_files = max(1, int(max_files))
        self._lock = threading.Lock()
        self._fh = open(self.path, "a" if append else "w", encoding="utf-8")

    def emit(self, type: str, **fields: Any) -> None:
        record: Dict[str, Any] = {"ts": wall_s(), "type": type}
        record.update(fields)
        line = json.dumps(record, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.max_bytes is not None and \
                    self._fh.tell() >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Shift segments up one index and start a fresh active file.
        Caller holds ``self._lock``.  Each shift is an atomic
        ``os.replace``; the segment at ``max_files - 1`` is overwritten
        by its younger neighbor, which drops the oldest data."""
        self._fh.close()
        if self.max_files > 1:
            for i in range(self.max_files - 2, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "w", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def segments(path: str) -> List[str]:
        """Existing segment paths oldest-first: ``path.N`` … ``path.1``,
        then the active ``path``."""
        path = str(path)
        directory = os.path.dirname(os.path.abspath(path)) or "."
        base = os.path.basename(path)
        indices = []
        try:
            names = os.listdir(directory)
        except OSError:
            names = []
        for name in names:
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    indices.append(int(suffix))
        out = [f"{path}.{i}" for i in sorted(indices, reverse=True)]
        if os.path.exists(path):
            out.append(path)
        return out

    @staticmethod
    def read(path: str) -> Iterator[Dict[str, Any]]:
        """Iterate the records of a JSONL event file, spanning rotated
        segments in order (oldest first, active file last)."""
        for segment in EventLog.segments(path):
            with open(segment, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        yield json.loads(line)


_default: Optional[EventLog] = None
_lock = threading.Lock()


def configure_event_log(path: Optional[str],
                        max_bytes: Optional[int] = None,
                        max_files: int = 5) -> Optional[EventLog]:
    """Point the process-global event sink at ``path`` (None closes and
    disables it).  Returns the active log."""
    global _default
    with _lock:
        if _default is not None:
            _default.close()
        _default = EventLog(path, max_bytes=max_bytes,
                            max_files=max_files) if path else None
    return _default


def get_event_log() -> Optional[EventLog]:
    return _default


def emit_event(type: str, **fields: Any) -> None:
    """Emit to the process-global log (a no-op when unconfigured) and
    mirror into the flight recorder's ``events`` ring (when installed) —
    the crash-window copy a dump preserves even with no JSONL sink."""
    log = _default
    if log is not None:
        log.emit(type, **fields)
    rec = get_flight_recorder()
    if rec is not None:
        rec.record("events", type, **fields)
