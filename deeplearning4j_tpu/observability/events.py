"""Structured JSONL event log for offline analysis.

One JSON object per line: ``{"ts": <wall seconds>, "type": <str>, ...}``.
Writers are thread-safe (one lock around the write; lines stay atomic)
and the module-level sink is a no-op until :func:`configure_event_log`
points it somewhere — the same off-by-default posture as the registry
and tracer.  Consumers are anything that reads JSONL: pandas, jq, or
``tools/trace_categorize.py``-style scripts.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, Iterator, Optional

from .clock import wall_s

__all__ = ["EventLog", "configure_event_log", "get_event_log", "emit_event"]


class EventLog:
    """Append-only JSONL writer."""

    def __init__(self, path: str, append: bool = True):
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a" if append else "w", encoding="utf-8")

    def emit(self, type: str, **fields: Any) -> None:
        record: Dict[str, Any] = {"ts": wall_s(), "type": type}
        record.update(fields)
        line = json.dumps(record, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @staticmethod
    def read(path: str) -> Iterator[Dict[str, Any]]:
        """Iterate the records of a JSONL event file."""
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    yield json.loads(line)


_default: Optional[EventLog] = None
_lock = threading.Lock()


def configure_event_log(path: Optional[str]) -> Optional[EventLog]:
    """Point the process-global event sink at ``path`` (None closes and
    disables it).  Returns the active log."""
    global _default
    with _lock:
        if _default is not None:
            _default.close()
        _default = EventLog(path) if path else None
    return _default


def get_event_log() -> Optional[EventLog]:
    return _default


def emit_event(type: str, **fields: Any) -> None:
    """Emit to the process-global log; silently a no-op when unconfigured."""
    log = _default
    if log is not None:
        log.emit(type, **fields)
