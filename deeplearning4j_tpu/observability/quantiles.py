"""Sliding-window quantile estimation for live SLO tracking.

Prometheus histograms answer "what was the p99 over the scrape interval"
*after* the scrape; an admission controller needs the answer *now*, from
the most recent requests only, without a registry round-trip.
``LatencyWindow`` is that primitive: a fixed-size ring of the last N
observations with exact (sorted-copy) quantile reads.  Exactness over a
bounded window beats a streaming sketch here — serving windows are small
(hundreds of requests), reads are rare (health probes, admission
decisions), and an approximate p99 that under-reads during a latency
spike is precisely the failure an SLO gate exists to catch.

Thread-safe: request threads observe, the health/admission path reads.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["LatencyWindow", "bucket_quantile"]


def bucket_quantile(cumulative: Sequence[Tuple[float, int]],
                    q: float) -> Optional[float]:
    """Nearest-rank quantile from cumulative histogram buckets
    ``[(upper_bound, cumulative_count), ...]`` (the
    ``Histogram.cumulative_buckets()`` shape, ending at ``(+Inf, n)``).

    Returns the upper bound of the bucket containing the rank — an upper
    estimate whose error is bounded by the bucket width, the same answer
    Prometheus' ``histogram_quantile`` gives at the bucket edge.  The
    ``+Inf`` bucket clamps to the largest finite bound (there is no
    meaningful upper edge beyond it).  None while the histogram is empty.
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    if not cumulative:
        return None
    total = cumulative[-1][1]
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    largest_finite = None
    for bound, count in cumulative:
        if bound != float("inf"):
            largest_finite = bound
        if count >= rank:
            return bound if bound != float("inf") else largest_finite
    return largest_finite


class LatencyWindow:
    """Fixed-size ring buffer of float observations with quantile reads.

    ``observe`` is O(1) under a lock; ``quantile`` copies and sorts the
    live window (O(n log n), n = window size) — cheap at the window sizes
    serving uses and only paid on health/admission reads.
    """

    def __init__(self, size: int = 512):
        if size <= 0:
            raise ValueError(f"window size must be positive, got {size}")
        self.size = int(size)
        self._ring: List[float] = [0.0] * self.size
        self._n = 0          # total observations ever
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring[self._n % self.size] = float(value)
            self._n += 1

    def __len__(self) -> int:
        return min(self._n, self.size)

    @property
    def count(self) -> int:
        """Total observations ever (not just the live window)."""
        return self._n

    def _live(self) -> List[float]:
        with self._lock:
            n = min(self._n, self.size)
            return self._ring[:n]

    def quantile(self, q: float) -> Optional[float]:
        """Exact q-quantile (nearest-rank) of the live window; None while
        empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        live = sorted(self._live())
        if not live:
            return None
        idx = min(len(live) - 1, int(q * len(live)))
        return live[idx]

    def snapshot(self) -> Dict[str, Optional[float]]:
        """One consistent read for health payloads: count + p50/p99."""
        live = sorted(self._live())
        if not live:
            return {"count": self._n, "p50": None, "p99": None}
        return {
            "count": self._n,
            "p50": live[min(len(live) - 1, int(0.50 * len(live)))],
            "p99": live[min(len(live) - 1, int(0.99 * len(live)))],
        }
