"""Shared clock helpers — the single source of timing truth.

Every interval in the package (tracer spans, metric timers, benchmark
clocks, listener throughput) reads ``monotonic_s()`` so measurements are
immune to wall-clock steps (NTP slew, DST); ``wall_s()`` exists for
timestamps that must be correlated with the outside world (event-log
records, scrape timestamps).  graftlint JX011 enforces this split:
``time.time()`` arithmetic is a lint error in library code.
"""
from __future__ import annotations

import time

__all__ = ["monotonic_s", "wall_s"]


def monotonic_s() -> float:
    """Monotonic seconds for interval measurement (never steps backwards)."""
    return time.perf_counter()


def wall_s() -> float:
    """Wall-clock seconds since the epoch — timestamps only, never
    intervals."""
    return time.time()
