"""deeplearning4j_tpu.observability — unified metrics + tracing.

One coherent telemetry layer for the training, parallel, and serving
tiers (the role TensorFlow's built-in metrics/tracing runtime plays,
Abadi et al. 2016), replacing the fragmented per-module counters the
reference stack grew (PerformanceListener wall clocks, external
OpProfiler, UI stats storage — SURVEY §5):

- :mod:`registry` — dependency-free Counter/Gauge/Histogram with label
  sets; thread-safe; process-global default + injectable instances;
- :mod:`exposition` — Prometheus text format + JSON snapshot (served on
  ``/metrics`` by both HTTP servers in ``serving/``);
- :mod:`tracer` — nested spans on monotonic clocks with cross-thread /
  cross-process context propagation and optional Xprof bridging;
- :mod:`events` — structured JSONL event log for offline analysis;
- :mod:`listener` — ``MetricsListener`` publishing score/throughput/
  grad-norm/device-memory from the ``TrainingListener`` hook points;
- :mod:`clock` — the monotonic/wall helpers everything above (and the
  benchmarks) source timings from;
- :mod:`quantiles` — sliding-window exact quantiles (``LatencyWindow``),
  the live p50/p99 read the serving tier's SLO admission control gates
  on (registry histograms answer scrape-interval questions, not
  "what is the p99 right now");
- :mod:`recorder` — the flight recorder: bounded ring buffers of recent
  spans/events/metric snapshots per subsystem channel, dumped as atomic
  checksummed JSON artifacts on crashes, preemptions, evictions, and
  SLO breaches (``/debug/flightrecorder`` on both HTTP servers);
- :mod:`health` — streaming anomaly detection (NaN loss/grads, EWMA
  spike, throughput regression, MFU regression, padding drift, serving
  p99/shed-rate) that flips ``/health`` to ``degraded``, can trigger an
  immediate checkpoint save, and (opt-in) stops training;
- :mod:`profiler` — the step profiler: per-step phase attribution
  (etl/h2d/dispatch/device/listener/forensics/checkpoint) with a
  SAMPLED device fence, dispatch-depth gauge, card-derived MFU,
  live-bytes watermarks vs the AX008 budgets, and Chrome-trace export
  (``/debug/profile`` on both HTTP servers).

Cost model: METRICS are on by default (the registry is plain host
arithmetic — serving ``/metrics`` and the training counters work out of
the box) and ``default_registry().disable()`` short-circuits every
instrument write to one bool check; TRACING is off by default (enable
via ``DL4J_TPU_TRACE=1|xprof`` or an injected ``Tracer``).  Nothing in
this package ever forces a device sync.
"""
from __future__ import annotations

from .clock import monotonic_s, wall_s
from .events import EventLog, configure_event_log, emit_event, get_event_log
from .exposition import CONTENT_TYPE, escape_label_value, render_text
from .health import (Detection, HealthConfig, HealthMonitor,
                     HealthTermination, get_health_monitor,
                     set_health_monitor)
from .profiler import (StepProfiler, chrome_trace, dump_chrome_trace,
                       load_chrome_trace, phase_summary, record_slices,
                       step_profiler_for, stepprof_enabled)
from .quantiles import LatencyWindow, bucket_quantile
from .recorder import (FlightRecorder, get_flight_recorder, load_dump,
                       set_flight_recorder)
from .registry import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry, default_registry,
                       set_default_registry)
from .tracer import Span, SpanContext, Tracer, get_tracer, set_default_tracer

__all__ = [
    "CONTENT_TYPE", "Counter", "DEFAULT_BUCKETS", "Detection", "EventLog",
    "FlightRecorder", "Gauge", "HealthConfig", "HealthMonitor",
    "HealthTermination", "Histogram", "LatencyWindow", "MetricsListener",
    "MetricsRegistry", "Span",
    "SpanContext", "StepProfiler", "Tracer", "bucket_quantile",
    "chrome_trace", "configure_event_log",
    "default_registry", "dump_chrome_trace",
    "emit_event", "escape_label_value", "get_event_log",
    "get_flight_recorder", "get_health_monitor", "get_tracer",
    "load_chrome_trace", "load_dump",
    "monotonic_s", "phase_summary", "record_slices", "render_text",
    "set_default_registry",
    "set_default_tracer", "set_flight_recorder", "set_health_monitor",
    "step_profiler_for", "stepprof_enabled", "wall_s",
]


def __getattr__(name):
    # MetricsListener imports train.listeners, which itself uses the
    # clock helpers here — resolve lazily to keep the import DAG acyclic
    if name == "MetricsListener":
        from .listener import MetricsListener
        return MetricsListener
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
