"""MetricsListener — publishes training telemetry into the metrics
registry from the existing :class:`TrainingListener` hook points.

Per iteration (at ``frequency`` granularity): score, iteration/examples
throughput, gradient global norm, device memory.

Sync discipline: the listener NEVER forces a device sync on its own.
Mid-fit the score is a still-async device scalar on every pipelined
path — plain ``fit`` (the graftaudit host-sync sweep: one
materialization per epoch, at the boundary) and ``ParallelWrapper``
alike — so per-iteration hooks SKIP score/grad-norm rather than
blocking the step queue, and record them in ``on_epoch_end`` where the
fit loop has already materialized the epoch's final loss.  A caller
that materializes per step (``fit_batch``) gets per-iteration score
for free, and ``force_device_sync=True`` opts in to one host sync per
``frequency`` iterations anywhere.

A disabled registry turns ``iteration_done`` into a single bool check:
no clocks, no fetches, no syncs.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .clock import monotonic_s
from .registry import MetricsRegistry, default_registry
from ..train.listeners import TrainingListener

__all__ = ["MetricsListener"]


class MetricsListener(TrainingListener):
    """Attach like any listener::

        net.add_listeners(MetricsListener())
        ...train...
        print(render_text(default_registry()))

    Metrics published (default registry unless one is injected):

    - ``model_iterations_total`` / ``model_examples_total`` counters
    - ``model_score`` gauge (most recent minibatch loss)
    - ``model_examples_per_sec`` / ``model_iterations_per_sec`` gauges
      (window = the last ``frequency`` iterations; the window containing
      the first, compile-dominated iteration is never reported)
    - ``model_grad_norm`` gauge (fused global norm from the train step)
    - ``model_epochs_total`` counter
    - ``device_memory_bytes{device,kind}`` gauges (TPU HBM; absent on
      backends that don't expose memory_stats)
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 frequency: int = 1, collect_grad_norms: bool = True,
                 collect_device_memory: bool = True,
                 force_device_sync: bool = False, event_log=None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.frequency = max(1, frequency)
        self.collect_grad_norms = collect_grad_norms
        self.collect_device_memory = collect_device_memory
        self.force_device_sync = force_device_sync
        self.event_log = event_log
        self._last_mono: Optional[float] = None
        self._last_iter: Optional[int] = None
        self._seen_iterations = 0
        self._ins = None

    # lazily bound ONCE (so a never-firing listener registers nothing,
    # and firing ones pay no per-iteration registry lookups)
    def _instruments(self):
        if self._ins is not None:
            return self._ins
        reg = self.registry
        self._ins = {
            "iters": reg.counter("model_iterations_total",
                                 "Train iterations observed by listeners"),
            "examples": reg.counter("model_examples_total",
                                    "Training examples consumed"),
            "score": reg.gauge("model_score",
                               "Most recent minibatch training loss"),
            "eps": reg.gauge("model_examples_per_sec",
                             "Steady-state examples/sec (compile window "
                             "excluded)"),
            "ips": reg.gauge("model_iterations_per_sec",
                             "Steady-state iterations/sec (compile window "
                             "excluded)"),
            "gnorm": reg.gauge("model_grad_norm",
                               "Global gradient L2 norm from the fused "
                               "train step"),
            "epochs": reg.counter("model_epochs_total",
                                  "Completed training epochs"),
        }
        return self._ins

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        reg = self.registry
        if not reg.enabled:        # no-op fast path: no clocks, no syncs
            return
        ins = self._instruments()
        now = monotonic_s()
        self._seen_iterations += 1
        batch = int(getattr(model, "last_batch_size", 0) or 0)
        ins["iters"].inc()
        if batch:
            ins["examples"].inc(batch)
        if iteration % self.frequency != 0:
            return
        # score: free when the fit path already materialized it (plain
        # fit); a DEVICE scalar (pipelined/wrapper mid-fit) reads the
        # window-drain boundary instead — the most recently drained
        # step's host value, stale by at most the dispatch depth, no
        # sync.  force_device_sync remains the only path that stalls
        # the step queue.
        raw_score = getattr(model, "_score", None)
        score_is_host = isinstance(raw_score, float)
        drained_at = getattr(model, "last_drained_iteration", -1)
        score = None
        if score_is_host:
            score = raw_score
        elif self.force_device_sync:
            score = float(model.get_score())
        elif isinstance(drained_at, int) and drained_at >= 0:
            # NOTE: deliberately does NOT flip score_is_host — the
            # grad-norm fetch below must keep gating on a truly drained
            # step queue, and with a boundary read the CURRENT step's
            # gstats are still in flight
            score = getattr(model, "last_drained_score", None)
        if score is not None:
            ins["score"].set(score)
        if self._last_mono is not None and self._last_iter is not None \
                and self._seen_iterations > self.frequency:
            # rate over the closed window; the very first window holds
            # the compile-dominated iteration and is skipped above
            dt = max(now - self._last_mono, 1e-9)
            iters = max(iteration - self._last_iter, 1)
            ins["ips"].set(iters / dt)
            if batch:
                ins["eps"].set(batch * iters / dt)
        self._last_mono = now
        self._last_iter = iteration
        if self.collect_grad_norms and (score_is_host
                                        or self.force_device_sync):
            gstats = getattr(model, "_last_grad_stats", None)
            if gstats is not None:
                # the step queue is already drained here (host score), so
                # this fetch is one cheap roundtrip per `frequency` iters
                ins["gnorm"].set(float(gstats["global_norm"]))
        if self.collect_device_memory:
            self._collect_memory(reg)
        if self.event_log is not None:
            self.event_log.emit("train_iteration", iteration=iteration,
                                epoch=epoch, score=score, batch_size=batch)

    def _collect_memory(self, reg: MetricsRegistry) -> None:
        try:
            import jax
            devices = jax.devices()
        except Exception:
            return
        g = reg.gauge("device_memory_bytes", "Device memory by kind",
                      ("device", "kind"))
        for i, dev in enumerate(devices):
            stats_fn = getattr(dev, "memory_stats", None)
            if stats_fn is None:
                continue
            try:
                st = stats_fn() or {}
            except Exception:
                continue
            for src, kind in (("bytes_in_use", "in_use"),
                              ("peak_bytes_in_use", "peak"),
                              ("bytes_limit", "limit")):
                if src in st:
                    g.labels(str(i), kind).set(float(st[src]))

    def on_epoch_end(self, model) -> None:
        if not self.registry.enabled:
            return
        ins = self._instruments()
        ins["epochs"].inc()
        # the fit loops materialize the epoch's final loss right before
        # this hook (one sync per epoch), so a host-float score — and
        # the grad-norm fetch behind the then-drained queue — is free
        # here; a still-device scalar (a custom loop) is skipped unless
        # force_device_sync, same rule as iteration_done
        raw = getattr(model, "_score", None)
        score = raw if isinstance(raw, float) else (
            float(model.get_score()) if self.force_device_sync else None)
        if score is not None:
            ins["score"].set(score)
            if self.collect_grad_norms:
                gstats = getattr(model, "_last_grad_stats", None)
                if gstats is not None:
                    ins["gnorm"].set(float(gstats["global_norm"]))
        if self.event_log is not None:
            self.event_log.emit("epoch_end", epoch=getattr(model, "epoch", -1),
                                score=score)
