"""Early stopping (reference ``deeplearning4j-nn/.../earlystopping/``)."""
from .config import EarlyStoppingConfiguration
from .result import EarlyStoppingResult
from .savers import InMemoryModelSaver, LocalFileModelSaver
from .scorecalc import (AccuracyScoreCalculator, DataSetLossCalculator)
from .terminations import (BestScoreEpochTerminationCondition,
                           InvalidScoreIterationTerminationCondition,
                           MaxEpochsTerminationCondition,
                           MaxScoreIterationTerminationCondition,
                           MaxTimeIterationTerminationCondition,
                           ScoreImprovementEpochTerminationCondition)
from .trainer import (EarlyStoppingGraphTrainer, EarlyStoppingMasterTrainer,
                      EarlyStoppingParallelTrainer, EarlyStoppingTrainer)

__all__ = [
    "AccuracyScoreCalculator", "BestScoreEpochTerminationCondition",
    "DataSetLossCalculator", "EarlyStoppingConfiguration",
    "EarlyStoppingResult", "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
    "EarlyStoppingMasterTrainer", "EarlyStoppingParallelTrainer",
    "InMemoryModelSaver",
    "InvalidScoreIterationTerminationCondition", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition", "MaxScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
]
