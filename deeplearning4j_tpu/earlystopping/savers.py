"""Model savers (reference ``earlystopping/saver/``)."""
from __future__ import annotations

import os

from ..utils import model_serializer


class InMemoryModelSaver:
    """Keep clones in memory (reference ``InMemoryModelSaver.java``)."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score):
        self._best = net.clone()

    def save_latest_model(self, net, score):
        self._latest = net.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Zip checkpoints on disk (reference ``LocalFileModelSaver.java``).

    Writes go through ``model_serializer.write_model``, which commits via
    the atomic temp-then-rename helper (``faulttolerance/atomic.py``): the
    frequent ``save_latest_model`` overwrite can never leave a truncated
    ``latestModel.zip`` behind a crash — readers always see the previous
    complete save or the new one."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, kind):
        return os.path.join(self.directory, f"{kind}Model.zip")

    def save_best_model(self, net, score):
        model_serializer.write_model(net, self._path("best"))

    def save_latest_model(self, net, score):
        model_serializer.write_model(net, self._path("latest"))

    def get_best_model(self):
        p = self._path("best")
        return model_serializer.restore_model(p) if os.path.exists(p) else None

    def get_latest_model(self):
        p = self._path("latest")
        return model_serializer.restore_model(p) if os.path.exists(p) else None
