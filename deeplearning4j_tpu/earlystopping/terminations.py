"""Termination conditions (reference ``earlystopping/termination/`` — both
epoch-level and iteration-level families)."""
from __future__ import annotations

import math
import time


class EpochTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch, score, minimize):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self.best = None
        self.since = 0

    def initialize(self):
        self.best, self.since = None, 0

    def terminate(self, epoch, score, minimize):
        if self.best is None:
            self.best = score
            return False
        improvement = (self.best - score) if minimize else (score - self.best)
        if improvement > self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        return self.since >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as a target."""

    def __init__(self, best_expected_score: float):
        self.target = float(best_expected_score)

    def terminate(self, epoch, score, minimize):
        return score <= self.target if minimize else score >= self.target


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, last_score):
        return (time.perf_counter() - self._start) >= self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop if the score exceeds a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, last_score):
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)
