"""Score calculators (reference ``earlystopping/scorecalc/``)."""
from __future__ import annotations

import numpy as np


class ScoreCalculator:
    """Compute a model score on held-out data; lower is better unless
    ``minimize_score`` is False."""
    minimize_score = True

    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over an iterator (reference
    ``scorecalc/DataSetLossCalculator.java``; ``average=True`` weights by
    batch size as the reference does)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        for batch in self.iterator:
            x, y, m, lm = net._normalize_batch(batch)
            if isinstance(x, list):  # graph batch
                s = net.score(inputs=x, labels=y)
                bs = int(np.asarray(x[0]).shape[0])
            else:
                s = net.score(x=x, y=y)
                bs = int(np.asarray(x).shape[0])
            total += s * bs
            n += bs
        # average=False: summed loss over all examples (reference semantics)
        return total / max(n, 1) if self.average else total


class AccuracyScoreCalculator(ScoreCalculator):
    """Classification accuracy (maximize)."""
    minimize_score = False

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net) -> float:
        return float(net.evaluate(self.iterator).accuracy())
