"""EarlyStoppingConfiguration (reference
``earlystopping/EarlyStoppingConfiguration.java`` Builder)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

from .savers import InMemoryModelSaver


@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    model_saver: Any = field(default_factory=InMemoryModelSaver)
    epoch_terminations: List[Any] = field(default_factory=list)
    iteration_terminations: List[Any] = field(default_factory=list)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1

    class Builder:
        def __init__(self):
            self._conf = EarlyStoppingConfiguration()

        def score_calculator(self, sc):
            self._conf.score_calculator = sc
            return self

        def model_saver(self, saver):
            self._conf.model_saver = saver
            return self

        def epoch_termination_conditions(self, *conds):
            self._conf.epoch_terminations = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._conf.iteration_terminations = list(conds)
            return self

        def save_last_model(self, b: bool = True):
            self._conf.save_last_model = bool(b)
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._conf.evaluate_every_n_epochs = int(n)
            return self

        def build(self):
            return self._conf

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()
