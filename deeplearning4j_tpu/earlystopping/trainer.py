"""EarlyStoppingTrainer (reference
``earlystopping/trainer/BaseEarlyStoppingTrainer.java:46`` — one class serves
both MultiLayerNetwork and ComputationGraph since fit/score share a surface).
"""
from __future__ import annotations

import logging
import math

from .result import EarlyStoppingResult
from .terminations import MaxEpochsTerminationCondition

log = logging.getLogger(__name__)


class EarlyStoppingTrainer:
    def __init__(self, config, net, train_iterator):
        self.config = config
        self.net = net
        self.train_iterator = train_iterator

    def _fit_epoch(self):
        """One training epoch; returns the iteration-termination condition
        that fired, or None.  Overridden by the master-driven variant."""
        conf = self.config
        if hasattr(self.train_iterator, "reset"):
            self.train_iterator.reset()
        for batch in self.train_iterator:
            # fit_batch: no epoch bookkeeping — this loop owns epochs
            last = self.net.fit_batch(batch)
            for c in conf.iteration_terminations:
                if c.terminate(last):
                    return c
        return None

    def fit(self) -> EarlyStoppingResult:
        conf = self.config
        for c in conf.epoch_terminations:
            c.initialize()
        for c in conf.iteration_terminations:
            c.initialize()
        if not self.net.params:
            self.net.init()

        minimize = (conf.score_calculator.minimize_score
                    if conf.score_calculator else True)
        best_score = math.inf if minimize else -math.inf
        best_epoch = -1
        score_vs_epoch = {}
        epoch = 0

        while True:
            # ---- one epoch, with iteration-level termination checks -------
            it_terminated = self._fit_epoch()
            if it_terminated is not None:
                details = type(it_terminated).__name__
                log.info("early stopping: iteration termination %s", details)
                if conf.save_last_model:
                    conf.model_saver.save_latest_model(self.net,
                                                       self.net.get_score())
                return EarlyStoppingResult(
                    termination_reason="IterationTerminationCondition",
                    termination_details=details,
                    score_vs_epoch=score_vs_epoch,
                    best_model_epoch=best_epoch, best_model_score=best_score,
                    total_epochs=epoch + 1,
                    best_model=conf.model_saver.get_best_model())

            # ---- end of epoch: score + save + epoch terminations ----------
            # best-model tracking only on epochs where the held-out score was
            # actually computed — the training loss lives on a different
            # scale and must not compete with calculator scores
            calculated = (conf.score_calculator is None or
                          epoch % conf.evaluate_every_n_epochs == 0)
            if calculated:
                score = (conf.score_calculator.calculate_score(self.net)
                         if conf.score_calculator else self.net.get_score())
                score_vs_epoch[epoch] = score
                improved = (score < best_score if minimize
                            else score > best_score)
                if improved:
                    best_score, best_epoch = score, epoch
                    conf.model_saver.save_best_model(self.net, score)
            else:
                score = best_score  # placeholder; not recorded/compared
            if conf.save_last_model:
                conf.model_saver.save_latest_model(self.net, score)

            for c in conf.epoch_terminations:
                # score-based conditions only fire on evaluated epochs
                if not calculated and not isinstance(
                        c, MaxEpochsTerminationCondition):
                    continue
                if c.terminate(epoch, score, minimize):
                    details = f"{type(c).__name__} at epoch {epoch}"
                    log.info("early stopping: %s", details)
                    return EarlyStoppingResult(
                        termination_reason="EpochTerminationCondition",
                        termination_details=details,
                        score_vs_epoch=score_vs_epoch,
                        best_model_epoch=best_epoch,
                        best_model_score=best_score,
                        total_epochs=epoch + 1,
                        best_model=conf.model_saver.get_best_model())
            epoch += 1


# reference has separate EarlyStoppingTrainer / EarlyStoppingGraphTrainer;
# the graph variant is the same loop here
EarlyStoppingGraphTrainer = EarlyStoppingTrainer

# reference ``EarlyStoppingParallelTrainer`` (scaleout module): the same
# loop driving a ParallelWrapper — the wrapper duck-types the model surface
# (fit_batch/get_score/params/init), so no separate implementation needed.
EarlyStoppingParallelTrainer = EarlyStoppingTrainer


class EarlyStoppingMasterTrainer(EarlyStoppingTrainer):
    """Early stopping where each epoch is one TrainingMaster pass over the
    data (reference ``spark/earlystopping/SparkEarlyStoppingTrainer`` /
    ``BaseSparkEarlyStoppingTrainer``: fit one RDD pass per epoch, score on
    the driver).  Iteration-level terminations don't apply — the master owns
    the inner loop, as the Spark workers do in the reference."""

    def __init__(self, config, net, master, train_iterator):
        super().__init__(config, net, train_iterator)
        self.master = master

    def _fit_epoch(self):
        if hasattr(self.train_iterator, "reset"):
            self.train_iterator.reset()
        self.master.fit(self.net, self.train_iterator)
        return None
