"""Model import (reference ``deeplearning4j-modelimport``): pure-Python HDF5
parsing + Keras model/weight mapping onto the config DSL."""
from .hdf5 import Hdf5Dataset, Hdf5File, Hdf5FormatError, Hdf5Group
from .hdf5_writer import Hdf5Writer, write_hdf5
from .trainedmodels import ImageNetLabels, TrainedModels, VGG16Helper
from .keras_export import export_keras_model, export_keras_sequential
from .keras import (KerasImportError, KerasModelImport, import_keras_model,
                    import_keras_sequential_model)

__all__ = ["Hdf5File", "Hdf5Group", "Hdf5Dataset", "Hdf5FormatError",
           "Hdf5Writer", "write_hdf5", "KerasModelImport",
           "KerasImportError", "import_keras_sequential_model",
           "import_keras_model", "ImageNetLabels", "TrainedModels",
           "VGG16Helper", "export_keras_sequential", "export_keras_model"]
