"""Minimal HDF5 writer (superblock v0, v1 object headers, symbol-table
groups, contiguous datasets, fixed/vlen string + numeric attributes).

Purpose: (a) export models in the Keras-readable weight layout without
h5py, (b) generate real HDF5 fixtures for the reader tests — the format
features emitted here (old-style groups, GCOL vlen strings) are exactly the
ones libhdf5 writes for Keras files, so round-trip tests exercise the same
code paths that real imports hit.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

__all__ = ["Hdf5Writer", "write_hdf5"]

UNDEF = 0xFFFFFFFFFFFFFFFF
# placeholder for not-yet-known global-heap addresses; patched in finalize.
# 8 high-entropy bytes make an accidental match in real data vanishingly rare
_ADDR_MAGIC = b"\xde\xad\xbe\xef\xfe\xed\xfa\xce"


def _pad8(b: bytes) -> bytes:
    return b + b"\x00" * ((-len(b)) % 8)


class _VlenStr:
    def __init__(self, values: List[str], dims: Tuple[int, ...]):
        self.values = values
        self.dims = dims


class Hdf5Writer:
    """``tree`` is nested dicts; leaves are np.ndarray.  ``attrs`` maps
    group-path -> {name: value} where value is str | [str] | int | float |
    np.ndarray | bytes (fixed string)."""

    def __init__(self):
        self.buf = bytearray()
        self._gheap: List[bytes] = []       # pending vlen payloads

    # ---------------------------------------------------------------- alloc
    def _alloc(self, size: int, align: int = 8) -> int:
        while len(self.buf) % align:
            self.buf.append(0)
        off = len(self.buf)
        self.buf.extend(b"\x00" * size)
        return off

    def _put(self, off: int, data: bytes):
        self.buf[off:off + len(data)] = data

    # ------------------------------------------------------------- messages
    @staticmethod
    def _msg(mtype: int, body: bytes) -> bytes:
        body = _pad8(body)
        return struct.pack("<HHB3x", mtype, len(body), 0) + body

    @staticmethod
    def _dataspace(dims: Tuple[int, ...]) -> bytes:
        body = struct.pack("<BBB5x", 1, len(dims), 0)
        for d in dims:
            body += struct.pack("<Q", d)
        return body

    @staticmethod
    def _dt_float(size: int) -> bytes:
        # class 1 (float) v1, little-endian IEEE; second bit-field byte is
        # the sign-bit position (31 for f32, 63 for f64)
        if size == 4:
            props = struct.pack("<HHBBBBI", 0, 32, 23, 8, 0, 23, 127)
        else:
            props = struct.pack("<HHBBBBI", 0, 64, 52, 11, 0, 52, 1023)
        sign_pos = size * 8 - 1
        return struct.pack("<BBBBI", 0x11, 0x20, sign_pos, 0x00,
                           size) + props

    @staticmethod
    def _dt_int(size: int, signed: bool = True) -> bytes:
        b0 = 0x08 if signed else 0
        return struct.pack("<BBBBI", 0x10, b0, 0, 0, size) + struct.pack(
            "<HH", 0, size * 8)

    @staticmethod
    def _dt_fixed_str(size: int) -> bytes:
        return struct.pack("<BBBBI", 0x13, 0, 0, 0, size)

    @staticmethod
    def _dt_vlen_str() -> bytes:
        base = Hdf5Writer._dt_fixed_str(1)
        return struct.pack("<BBBBI", 0x19, 0x01, 0, 0, 16) + base

    @staticmethod
    def _np_datatype(arr: np.ndarray) -> bytes:
        if arr.dtype.kind == "f":
            return Hdf5Writer._dt_float(arr.dtype.itemsize)
        if arr.dtype.kind in "iu":
            return Hdf5Writer._dt_int(arr.dtype.itemsize,
                                      arr.dtype.kind == "i")
        raise ValueError(f"unsupported dtype {arr.dtype}")

    # ----------------------------------------------------------- attributes
    def _attr_msg(self, name: str, value: Any) -> bytes:
        nameb = name.encode() + b"\x00"
        if isinstance(value, str):
            value = _VlenStr([value], ())
        elif (isinstance(value, (list, tuple)) and value
              and isinstance(value[0], str)):
            value = _VlenStr(list(value), (len(value),))
        if isinstance(value, _VlenStr):
            dt = self._dt_vlen_str()
            ds = self._dataspace(value.dims)
            data = b""
            for s in value.values:
                payload = s.encode()
                self._gheap.append(payload)
                idx = len(self._gheap)
                # size(4) addr(8, magic placeholder patched in finalize) idx(4)
                data += struct.pack("<I", len(payload)) + _ADDR_MAGIC \
                    + struct.pack("<I", idx)
        elif isinstance(value, bytes):
            dt = self._dt_fixed_str(len(value))
            ds = self._dataspace(())
            data = value
        else:
            arr = np.atleast_1d(np.asarray(value))
            scalar = np.asarray(value).ndim == 0
            dt = self._np_datatype(arr)
            ds = self._dataspace(() if scalar else arr.shape)
            data = arr.tobytes()
        body = struct.pack("<BxHHH", 1, len(nameb), len(dt), len(ds))
        body += _pad8(nameb) + _pad8(dt) + _pad8(ds) + data
        return self._msg(0x000C, body)

    # ------------------------------------------------------------- datasets
    def _write_dataset(self, arr: np.ndarray, attrs: Dict[str, Any],
                       chunks: Optional[Tuple[int, ...]] = None,
                       gzip_level: Optional[int] = None) -> int:
        arr = np.ascontiguousarray(arr)
        msgs = [
            self._msg(0x0001, self._dataspace(arr.shape)),
            self._msg(0x0003, self._np_datatype(arr)),
        ]
        if chunks is None:
            data_addr = self._alloc(arr.nbytes)
            self._put(data_addr, arr.tobytes())
            msgs.append(self._msg(0x0008, struct.pack(
                "<BBQQ", 3, 1, data_addr, arr.nbytes)))
        else:
            msgs.extend(self._write_chunked(arr, chunks, gzip_level))
        for k, v in (attrs or {}).items():
            msgs.append(self._attr_msg(k, v))
        return self._write_object_header(msgs)

    def _write_chunked(self, arr: np.ndarray, chunks: Tuple[int, ...],
                       gzip_level: Optional[int]) -> List[bytes]:
        import zlib as _zlib
        ndims = arr.ndim
        es = arr.dtype.itemsize
        entries = []  # (offsets, size, addr)
        grid = [range(0, arr.shape[d], chunks[d]) for d in range(ndims)]
        import itertools
        for origin in itertools.product(*grid):
            sl = tuple(slice(o, min(o + chunks[d], arr.shape[d]))
                       for d, o in enumerate(origin))
            block = np.zeros(chunks, arr.dtype)
            block[tuple(slice(0, s.stop - s.start) for s in sl)] = arr[sl]
            raw = block.tobytes()
            if gzip_level is not None:
                raw = _zlib.compress(raw, gzip_level)
            addr = self._alloc(len(raw))
            self._put(addr, raw)
            entries.append((origin, len(raw), addr))
        key_size = 8 + 8 * (ndims + 1)
        tree_addr = self._alloc(8 + 16 + len(entries) * (key_size + 8)
                                + key_size)
        self._put(tree_addr, b"TREE" + struct.pack(
            "<BBHQQ", 1, 0, len(entries), UNDEF, UNDEF))
        p = tree_addr + 24
        for (origin, size, addr) in entries:
            key = struct.pack("<II", size, 0)
            for o in origin:
                key += struct.pack("<Q", o)
            key += struct.pack("<Q", 0)  # element-offset dim (always 0)
            self._put(p, key)
            self._put(p + key_size, struct.pack("<Q", addr))
            p += key_size + 8
        # final (upper-bound) key: one chunk past the end in every dim —
        # libhdf5 binary-searches the keys, a zeroed bound breaks lookup
        # of edge chunks
        bound = struct.pack("<II", 0, 0)
        for d in range(ndims):
            end = ((arr.shape[d] + chunks[d] - 1) // chunks[d]) * chunks[d]
            bound += struct.pack("<Q", end)
        bound += struct.pack("<Q", 0)
        self._put(p, bound)
        msgs = [self._msg(0x0008, struct.pack(
            "<BBBQ", 3, 2, ndims + 1, tree_addr)
            + b"".join(struct.pack("<I", c) for c in chunks)
            + struct.pack("<I", es))]
        if gzip_level is not None:
            # filter pipeline v1: gzip (id 1), one client value (level)
            body = struct.pack("<BB6x", 1, 1)
            body += struct.pack("<HHHH", 1, 0, 1, 1)  # id,namelen,flags,ncv
            body += struct.pack("<I", gzip_level) + b"\x00" * 4  # pad ncv odd
            msgs.append(self._msg(0x000B, body))
        return msgs

    def _write_object_header(self, msgs: List[bytes]) -> int:
        total = sum(len(m) for m in msgs)
        addr = self._alloc(16 + total)
        self._put(addr, struct.pack("<BxHII4x", 1, len(msgs), 1, total))
        off = addr + 16
        for m in msgs:
            self._put(off, m)
            off += len(m)
        return addr

    # --------------------------------------------------------------- groups
    def _write_group(self, children: Dict[str, int],
                     attrs: Dict[str, Any]) -> int:
        # local heap with child names
        names = sorted(children)
        heap_data = bytearray(b"\x00" * 8)  # offset 0 reserved (empty name)
        offsets = {}
        for n in names:
            offsets[n] = len(heap_data)
            heap_data.extend(n.encode() + b"\x00")
            while len(heap_data) % 8:
                heap_data.append(0)
        heap_data_addr = self._alloc(max(len(heap_data), 8))
        self._put(heap_data_addr, bytes(heap_data))
        heap_addr = self._alloc(32)
        # free-list head = 1 (H5HL_FREE_NULL): no free blocks
        self._put(heap_addr, b"HEAP" + struct.pack(
            "<B3xQQQ", 0, len(heap_data), 1, heap_data_addr))
        # single SNOD with all entries (names must be heap-offset sorted)
        snod_addr = self._alloc(8 + 40 * len(names))
        self._put(snod_addr, b"SNOD" + struct.pack("<BxH", 1, len(names)))
        p = snod_addr + 8
        for n in names:
            self._put(p, struct.pack("<QQI4x16x", offsets[n], children[n], 0))
            p += 40
        # btree with one entry -> snod
        btree_addr = self._alloc(8 + 16 + 8 + 16)
        self._put(btree_addr, b"TREE" + struct.pack(
            "<BBHQQ", 0, 0, 1, UNDEF, UNDEF))
        p = btree_addr + 24
        self._put(p, struct.pack("<Q", 0))            # key0
        self._put(p + 8, struct.pack("<Q", snod_addr))  # child
        self._put(p + 16, struct.pack("<Q", offsets[names[-1]] if names
                                      else 0))       # key1
        msgs = [self._msg(0x0011, struct.pack("<QQ", btree_addr, heap_addr))]
        for k, v in (attrs or {}).items():
            msgs.append(self._attr_msg(k, v))
        return self._write_object_header(msgs)

    # -------------------------------------------------------------- finalize
    def write(self, tree: Dict[str, Any],
              attrs: Optional[Dict[str, Dict[str, Any]]] = None) -> bytes:
        """tree: nested dicts, leaves np.ndarray (or (array, attr_dict)).
        attrs: {"/": {...}, "/group/path": {...}} extra group attributes."""
        attrs = attrs or {}
        self.buf = bytearray(b"\x00" * 96)  # superblock v0 placeholder

        def build(node: Dict[str, Any], path: str) -> int:
            children = {}
            for name, sub in node.items():
                if isinstance(sub, dict):
                    children[name] = build(sub, f"{path}{name}/")
                elif isinstance(sub, tuple):
                    # (array, attrs[, chunks[, gzip_level]])
                    extra = list(sub[2:]) + [None, None]
                    children[name] = self._write_dataset(
                        np.asarray(sub[0]), sub[1], chunks=extra[0],
                        gzip_level=extra[1])
                else:
                    children[name] = self._write_dataset(np.asarray(sub), {})
            return self._write_group(children,
                                     attrs.get(path.rstrip("/") or "/", {}))

        root_addr = build(tree, "/")
        gheap_addr = self._write_gheap()
        self._patch_refs(gheap_addr)
        # superblock v0
        sb = b"\x89HDF\r\n\x1a\n" + struct.pack(
            "<BBBxBBBxHHI", 0, 0, 0, 0, 8, 8, 4, 16, 0)
        sb += struct.pack("<QQQQ", 0, UNDEF, len(self.buf), UNDEF)
        sb += struct.pack("<QQI4x16x", 0, root_addr, 0)
        self.buf[:len(sb)] = sb
        return bytes(self.buf)

    def _write_gheap(self) -> int:
        if not self._gheap:
            return UNDEF
        objs = b""
        for i, payload in enumerate(self._gheap, start=1):
            objs += struct.pack("<HH4xQ", i, 1, len(payload))
            objs += _pad8(payload)
        # libhdf5 requires collections of at least 4096 bytes; the tail is
        # a free-space object (index 0, size = remaining bytes incl. its
        # own 16-byte header)
        total = max(16 + len(objs) + 16, 4096)
        free = total - 16 - len(objs)
        addr = self._alloc(total)
        self._put(addr, b"GCOL" + struct.pack("<B3xQ", 1, total) + objs
                  + struct.pack("<HH4xQ", 0, 0, free))
        return addr

    def _patch_refs(self, gheap_addr: int):
        """Patch the global-heap address into every vlen reference: the
        references were emitted with a magic 8-byte placeholder (attr bytes
        are built before their final file position is known)."""
        for payload_idx, payload in enumerate(self._gheap, start=1):
            needle = (struct.pack("<I", len(payload)) + _ADDR_MAGIC
                      + struct.pack("<I", payload_idx))
            start = 0
            while True:
                pos = self.buf.find(needle, start)
                if pos < 0:
                    break
                self._put(pos + 4, struct.pack("<Q", gheap_addr))
                start = pos + 16


def write_hdf5(path: str, tree: Dict[str, Any],
               attrs: Optional[Dict[str, Dict[str, Any]]] = None) -> None:
    data = Hdf5Writer().write(tree, attrs)
    with open(path, "wb") as fh:
        fh.write(data)
