"""Export MultiLayerNetwork models to Keras-2-layout HDF5.

The reverse of ``keras.py`` (the reference ships import only; export
closes the interchange loop so models trained here load in Keras/DL4J
tooling).  Files are written with our own ``Hdf5Writer`` — the emitted
format (v1 headers, symbol-table groups, GCOL vlen strings) is exactly
what libhdf5 produces, so real h5py/Keras can read them (cross-validated
in tests with h5py).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .hdf5_writer import Hdf5Writer

__all__ = ["export_keras_sequential"]

_ACT_INV = {
    "relu": "relu", "tanh": "tanh", "sigmoid": "sigmoid",
    "softmax": "softmax", "identity": "linear", "elu": "elu",
    "selu": "selu", "softplus": "softplus", "softsign": "softsign",
    "hardsigmoid": "hard_sigmoid", "swish": "swish", "gelu": "gelu",
}


def _act_name(layer) -> str:
    a = layer.resolved("activation", "identity")
    if a not in _ACT_INV:
        raise ValueError(f"activation '{a}' has no Keras name")
    return _ACT_INV[a]


def _np(p) -> np.ndarray:
    return np.asarray(p, np.float32)


def _pair_list(v) -> list:
    if isinstance(v, (tuple, list)):
        return [int(x) for x in v]
    return [int(v), int(v)]


def _export_layer(i: int, lc, params: Dict[str, Any],
                  state: Dict[str, Any], input_shape: Optional[list],
                  input_kind: Optional[str] = None):
    """Returns (keras_layer_config, {weight_name: array}) or None to skip."""
    cls = type(lc).__name__
    name = lc.name or f"layer_{i}"
    conf: Dict[str, Any] = {"name": name}
    if input_shape is not None:
        conf["batch_input_shape"] = input_shape
    if cls in ("DenseLayer", "OutputLayer", "CenterLossOutputLayer",
               "RnnOutputLayer"):   # Keras Dense maps over [b,t,f] too
        conf.update(units=int(lc.n_out), activation=_act_name(lc),
                    use_bias=bool(getattr(lc, "has_bias", True)))
        w = {"kernel:0": _np(params["W"])}
        if "b" in params:
            w["bias:0"] = _np(params["b"])
        return {"class_name": "Dense", "config": conf}, w
    if cls == "ConvolutionLayer":
        pad = _pair_list(getattr(lc, "padding", (0, 0)))
        dil = _pair_list(getattr(lc, "dilation", (1, 1)))
        if lc.convolution_mode != "same" and any(pad):
            raise ValueError(
                f"layer {name}: explicit padding {pad} has no Keras "
                "Sequential equivalent (use convolution_mode='same' or "
                "zero padding layers)")
        if any(d != 1 for d in dil):
            raise ValueError(
                f"layer {name}: dilation {dil} is not exported")
        conf.update(filters=int(lc.n_out),
                    kernel_size=_pair_list(lc.kernel_size),
                    strides=_pair_list(lc.stride),
                    padding="same" if lc.convolution_mode == "same"
                    else "valid",
                    activation=_act_name(lc),
                    use_bias=bool(lc.has_bias))
        w = {"kernel:0": _np(params["W"])}   # HWIO both sides
        if "b" in params:
            w["bias:0"] = _np(params["b"])
        return {"class_name": "Conv2D", "config": conf}, w
    if cls == "SubsamplingLayer":
        kname = ("MaxPooling2D" if lc.pooling_type == "max"
                 else "AveragePooling2D")
        conf.update(pool_size=_pair_list(lc.kernel_size),
                    strides=_pair_list(lc.stride))
        return {"class_name": kname, "config": conf}, {}
    if cls == "BatchNormalization":
        conf.update(epsilon=float(lc.eps), momentum=float(lc.decay))
        if state.get("mean") is None or state.get("var") is None:
            raise ValueError(
                f"layer {name}: BatchNormalization has no moving statistics "
                "in net.state — initialize/train the network before export")
        w = {}
        if "gamma" in params:
            w["gamma:0"] = _np(params["gamma"])
            w["beta:0"] = _np(params["beta"])
        w["moving_mean:0"] = _np(state["mean"])
        w["moving_variance:0"] = _np(state["var"])
        return {"class_name": "BatchNormalization", "config": conf}, w
    if cls == "LSTM":
        h = int(lc.n_out)
        gate = getattr(lc, "gate_activation", "sigmoid")
        if gate not in _ACT_INV:
            raise ValueError(
                f"layer {name}: gate activation '{gate}' has no Keras name")
        conf.update(units=h, activation=_act_name(lc),
                    recurrent_activation=_ACT_INV[gate],
                    return_sequences=True)

        def reorder(m):  # ours i,f,o,g(=c) -> keras i,f,c,o
            blocks = [m[..., g * h:(g + 1) * h] for g in range(4)]
            return np.concatenate(
                [blocks[0], blocks[1], blocks[3], blocks[2]], axis=-1)

        return {"class_name": "LSTM", "config": conf}, {
            "kernel:0": reorder(_np(params["W"])),
            "recurrent_kernel:0": reorder(_np(params["U"])),
            "bias:0": reorder(_np(params["b"]).reshape(1, -1)).reshape(-1)}
    if cls == "SimpleRnn":
        conf.update(units=int(lc.n_out), activation=_act_name(lc),
                    return_sequences=True)
        return {"class_name": "SimpleRNN", "config": conf}, {
            "kernel:0": _np(params["W"]),
            "recurrent_kernel:0": _np(params["U"]),
            "bias:0": _np(params["b"])}
    if cls == "EmbeddingLayer":
        conf.update(input_dim=int(lc.n_in), output_dim=int(lc.n_out))
        return {"class_name": "Embedding", "config": conf}, {
            "embeddings:0": _np(params["W"])}
    if cls == "ActivationLayer":
        conf.update(activation=_act_name(lc))
        return {"class_name": "Activation", "config": conf}, {}
    if cls == "DropoutLayer":
        conf.update(rate=1.0 - float(lc.dropout))
        return {"class_name": "Dropout", "config": conf}, {}
    if cls == "GlobalPoolingLayer":
        dim = "1D" if input_kind == "rnn" else "2D"
        kname = (f"GlobalMaxPooling{dim}" if lc.pooling_type == "max"
                 else f"GlobalAveragePooling{dim}")
        return {"class_name": kname, "config": conf}, {}
    raise ValueError(
        f"layer {name} ({cls}) has no Keras export mapping")


def _input_shape(itype) -> Optional[list]:
    if itype is None:
        return None
    if itype.kind == "ff":
        return [None, int(itype.size)]
    if itype.kind == "rnn":
        t = itype.timesteps
        return [None, None if not t or t < 0 else int(t), int(itype.size)]
    if itype.kind in ("cnn", "cnnflat"):
        return [None, int(itype.height), int(itype.width),
                int(itype.channels)]
    return None


def export_keras_sequential(net, path: Optional[str] = None) -> bytes:
    """Write ``net`` (MultiLayerNetwork) as a Keras-2 Sequential
    ``model.save()``-layout HDF5; returns the bytes (and writes ``path``
    when given)."""
    layer_entries: List[dict] = []
    tree: Dict[str, Any] = {"model_weights": {}}
    attrs: Dict[str, Dict[str, Any]] = {}
    layer_names: List[str] = []
    layer_itypes = getattr(net.conf, "layer_input_types", None) or []
    for i, lc in enumerate(net.layers):
        ishape = _input_shape(net.conf.input_type) if i == 0 else None
        ikind = (layer_itypes[i].kind if i < len(layer_itypes)
                 and layer_itypes[i] is not None else None)
        entry = _export_layer(i, lc, net.params.get(f"layer_{i}", {}),
                              net.state.get(f"layer_{i}", {}), ishape,
                              input_kind=ikind)
        kconf, weights = entry
        lname = kconf["config"]["name"]
        layer_entries.append(kconf)
        layer_names.append(lname)
        group: Dict[str, Any] = {}
        wnames = []
        for wn, arr in weights.items():
            group[wn] = arr
            wnames.append(f"{lname}/{wn}")
        tree["model_weights"][lname] = group
        attrs[f"/model_weights/{lname}"] = {"weight_names": wnames}
    config = {"class_name": "Sequential",
              "config": {"name": "sequential", "layers": layer_entries}}
    attrs["/"] = {"model_config": json.dumps(config),
                  "keras_version": "2.1.6", "backend": "tensorflow"}
    attrs["/model_weights"] = {"layer_names": layer_names,
                               "backend": "tensorflow"}
    data = Hdf5Writer().write(tree, attrs)
    if path:
        with open(path, "wb") as fh:
            fh.write(data)
    return data


_EW_TO_KERAS = {"add": "Add", "subtract": "Subtract", "product": "Multiply",
                "average": "Average", "max": "Maximum"}


def export_keras_model(net, path: Optional[str] = None) -> bytes:
    """Write a ComputationGraph as a Keras functional ``Model`` HDF5
    (inverse of ``import_keras_model``).  Covers LayerVertex (with the
    Sequential layer mappings), ElementWise merge vertices, and
    MergeVertex → Concatenate; other vertex types raise."""
    from ..nn.conf.computation_graph import (ElementWiseVertex, LayerVertex,
                                             MergeVertex)
    conf = net.conf
    layer_entries: List[dict] = []
    tree: Dict[str, Any] = {"model_weights": {}}
    attrs: Dict[str, Dict[str, Any]] = {}
    layer_names: List[str] = []

    for name in conf.network_inputs:
        idx = conf.network_inputs.index(name)
        it = (conf.input_types[idx] if idx < len(conf.input_types) else None)
        shape = _input_shape(it)
        if shape is None:
            raise ValueError(f"network input '{name}' needs an InputType "
                             "for Keras export")
        layer_entries.append({
            "class_name": "InputLayer", "name": name,
            "config": {"name": name, "batch_input_shape": shape},
            "inbound_nodes": []})

    for name in conf.topological_order:
        v = conf.vertices[name]
        inbound = [[[src, 0, 0, {}] for src in conf.vertex_inputs[name]]]
        if isinstance(v, ElementWiseVertex):
            if v.op not in _EW_TO_KERAS:
                raise ValueError(f"vertex {name}: elementwise op '{v.op}' "
                                 "has no Keras merge layer")
            layer_entries.append({
                "class_name": _EW_TO_KERAS[v.op], "name": name,
                "config": {"name": name}, "inbound_nodes": inbound})
            continue
        if isinstance(v, MergeVertex):
            layer_entries.append({
                "class_name": "Concatenate", "name": name,
                "config": {"name": name}, "inbound_nodes": inbound})
            continue
        if not isinstance(v, LayerVertex):
            raise ValueError(
                f"vertex {name} ({type(v).__name__}) has no Keras export "
                "mapping")
        itypes = conf.vertex_input_types.get(name) or [None]
        ikind = itypes[0].kind if itypes and itypes[0] is not None else None
        kconf, weights = _export_layer(
            0, v.layer, net.params.get(name, {}), net.state.get(name, {}),
            None, input_kind=ikind)
        kconf["config"]["name"] = name
        kconf["name"] = name
        kconf["inbound_nodes"] = inbound
        layer_entries.append(kconf)
        layer_names.append(name)
        group = {}
        wnames = []
        for wn, arr in weights.items():
            group[wn] = arr
            wnames.append(f"{name}/{wn}")
        tree["model_weights"][name] = group
        attrs[f"/model_weights/{name}"] = {"weight_names": wnames}

    config = {"class_name": "Model", "config": {
        "name": "model", "layers": layer_entries,
        "input_layers": [[n, 0, 0] for n in conf.network_inputs],
        "output_layers": [[n, 0, 0] for n in conf.network_outputs]}}
    attrs["/"] = {"model_config": json.dumps(config),
                  "keras_version": "2.1.6", "backend": "tensorflow"}
    attrs["/model_weights"] = {"layer_names": layer_names,
                               "backend": "tensorflow"}
    data = Hdf5Writer().write(tree, attrs)
    if path:
        with open(path, "wb") as fh:
            fh.write(data)
    return data
