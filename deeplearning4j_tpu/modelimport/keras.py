"""Keras HDF5 model import (reference ``deeplearning4j-modelimport``:
``KerasModelImport.java:50-157`` entry points, ``KerasSequentialModel.java``,
``KerasLayer.java:42`` registry of layer mappers).

Reads a Keras 1.x/2.x ``model.save()`` HDF5 file with the pure-Python parser
(``hdf5.py``), maps ``model_config`` onto our configuration DSL, builds a
``MultiLayerNetwork``, and copies the weights in (transposing/reordering
where conventions differ — e.g. Keras LSTM gate order i,f,c,o vs our
i,f,o,g).  TF channel-last conventions are assumed (the DL4J importer's
default for TF-backend files).

Supported layers: Dense, Activation, Dropout, Flatten, Conv1D/2D,
MaxPooling1D/2D, AveragePooling1D/2D, Global*Pooling1D/2D, ZeroPadding2D,
UpSampling2D, BatchNormalization, LSTM, SimpleRNN, Embedding, Reshape,
Permute, RepeatVector, TimeDistributed, and the advanced activations
LeakyReLU / ELU / ThresholdedReLU (reference registry
``KerasLayer.java:42`` + ``layers/advanced/activations/``).  Additional
classes can be plugged in with :func:`register_keras_layer` (the
``layers/custom/`` registry hook).  Unsupported layers raise
``KerasImportError`` naming the layer class (reference
``UnsupportedKerasConfigurationException``).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf.input_type import InputType
from ..nn.conf.multi_layer import NeuralNetConfiguration
from ..nn.conf.updaters import Sgd
from ..nn.layers.convolution import ConvolutionLayer, SubsamplingLayer
from ..nn.layers.feedforward import (ActivationLayer, DenseLayer,
                                     DropoutLayer, EmbeddingLayer,
                                     OutputLayer)
from ..nn.layers.normalization import BatchNormalization
from ..nn.layers.pooling import GlobalPoolingLayer
from ..nn.layers.recurrent import LSTM, RnnOutputLayer, SimpleRnn
from ..nn.multilayer import MultiLayerNetwork
from .hdf5 import Hdf5File, Hdf5FormatError

__all__ = ["KerasModelImport", "KerasImportError",
           "import_keras_sequential_model", "import_keras_model",
           "register_keras_layer", "KerasLayerMapping"]


class KerasImportError(ValueError):
    pass


# Custom layer mappers (reference KerasLayer.registerCustomLayer /
# ``layers/custom/``): class name -> fn(conf, is_last, rnn_input) -> _LayerMap
_CUSTOM_LAYERS: Dict[str, Any] = {}


def register_keras_layer(class_name: str, mapper) -> None:
    """Register an import mapper for a custom Keras layer class.

    ``mapper(conf: dict, is_last: bool, rnn_input: bool) ->
    KerasLayerMapping`` — build a layer conf plus a weight-copy function
    (``KerasLayerMapping(conf, copy_fn)``; ``copy_fn(keras_weights) ->
    params dict``).
    """
    _CUSTOM_LAYERS[class_name] = mapper


_ACT_MAP = {
    "relu": "relu", "tanh": "tanh", "sigmoid": "sigmoid",
    "softmax": "softmax", "linear": "identity", "elu": "elu",
    "selu": "selu", "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "gelu": "gelu",
}


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if name not in _ACT_MAP:
        raise KerasImportError(f"unsupported Keras activation '{name}'")
    return _ACT_MAP[name]


def _cfg(layer: Dict[str, Any]) -> Dict[str, Any]:
    return layer.get("config", {})


def _input_type_from(conf: Dict[str, Any]) -> Optional[InputType]:
    shape = conf.get("batch_input_shape") or conf.get("batch_shape")
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:  # [timesteps, features]
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:  # [h, w, c] channels_last
        return InputType.convolutional(dims[0], dims[1], dims[2])
    raise KerasImportError(f"cannot map input shape {shape}")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


class KerasLayerMapping:
    """One imported layer: our conf + a weight-copy function.  Public —
    custom mappers registered via :func:`register_keras_layer` return it."""

    def __init__(self, conf=None, copy=None):
        self.conf = conf
        self.copy = copy  # fn(keras_weights: dict[str, np.ndarray]) -> params


_LayerMap = KerasLayerMapping   # internal alias used by the built-in mappers


def _map_layer(cls: str, conf: Dict[str, Any], is_last: bool,
               rnn_input: bool = False) -> _LayerMap:
    name = conf.get("name")
    if cls in _CUSTOM_LAYERS:
        return _CUSTOM_LAYERS[cls](conf, is_last, rnn_input)
    if cls == "TimeDistributed":
        # wrapper: apply the inner layer per timestep — our dense/activation
        # layers already operate on the trailing feature axis of [b,t,f],
        # so for those the wrapper reduces to the inner mapping with rnn
        # semantics.  Spatial/recurrent inner layers would need real
        # per-step lifting — refuse rather than import a wrong network.
        inner = conf.get("layer") or {}
        inner_cls = inner.get("class_name", "")
        if inner_cls not in ("Dense", "Activation", "Dropout"):
            raise KerasImportError(
                f"unsupported TimeDistributed inner layer '{inner_cls}' "
                "(only Dense/Activation/Dropout map directly)")
        inner_conf = dict(_cfg(inner))
        inner_conf.setdefault("name", name)
        return _map_layer(inner_cls, inner_conf,
                          is_last=is_last, rnn_input=True)
    if cls == "LeakyReLU":
        alpha = float(conf.get("alpha", conf.get("negative_slope", 0.3)))
        return _LayerMap(ActivationLayer(
            name=name, activation=f"leakyrelu:{alpha}"), lambda w: {})
    if cls == "ELU":
        alpha = float(conf.get("alpha", 1.0))
        return _LayerMap(ActivationLayer(
            name=name, activation=f"elu:{alpha}"), lambda w: {})
    if cls == "ThresholdedReLU":
        theta = float(conf.get("theta", 1.0))
        return _LayerMap(ActivationLayer(
            name=name, activation=f"thresholdedrelu:{theta}"), lambda w: {})
    if cls == "Reshape":
        from ..nn.layers.misc import ReshapeLayer
        return _LayerMap(ReshapeLayer(
            name=name, target_shape=tuple(conf["target_shape"])),
            lambda w: {})
    if cls == "Permute":
        from ..nn.layers.misc import PermuteLayer
        return _LayerMap(PermuteLayer(name=name, dims=tuple(conf["dims"])),
                         lambda w: {})
    if cls == "RepeatVector":
        from ..nn.layers.misc import RepeatVector
        return _LayerMap(RepeatVector(name=name, n=int(conf["n"])),
                         lambda w: {})
    if cls == "Dense":
        act = _act(conf.get("activation"))
        n_out = int(conf["units"] if "units" in conf else conf["output_dim"])
        use_bias = conf.get("bias", conf.get("use_bias", True))
        if is_last:
            loss = "mcxent" if act == "softmax" else "mse"
            if rnn_input:
                # Keras Dense over [b,t,f] is time-distributed; keep the
                # time axis (RnnOutputLayer) instead of auto-flattening
                lc = RnnOutputLayer(name=name, n_out=n_out, activation=act,
                                    loss=loss, has_bias=use_bias)
            else:
                lc = OutputLayer(name=name, n_out=n_out, activation=act,
                                 loss=loss, has_bias=use_bias)
        else:
            lc = DenseLayer(name=name, n_out=n_out, activation=act,
                            has_bias=use_bias)

        def copy(w):
            out = {"W": w.get("kernel", w.get("W"))}
            if use_bias:
                out["b"] = w.get("bias", w.get("b"))
            return out

        return _LayerMap(lc, copy)
    if cls == "Activation":
        return _LayerMap(ActivationLayer(name=name,
                                         activation=_act(conf["activation"])),
                         lambda w: {})
    if cls == "Dropout":
        rate = float(conf.get("rate", conf.get("p", 0.5)))
        # Keras rate = drop probability; our dropout config keeps the
        # reference's retain-probability convention
        return _LayerMap(DropoutLayer(name=name, dropout=1.0 - rate),
                         lambda w: {})
    if cls == "Flatten":
        return _LayerMap(None, None)  # handled by auto preprocessor insertion
    if cls in ("Conv2D", "Convolution2D"):
        n_out = int(conf.get("filters", conf.get("nb_filter", 0)))
        if "kernel_size" in conf:
            kernel = _pair(conf["kernel_size"])
        else:  # Keras 1: nb_row / nb_col
            kernel = (int(conf["nb_row"]), int(conf["nb_col"]))
        stride = _pair(conf.get("strides", conf.get("subsample", (1, 1))))
        padding = conf.get("padding", conf.get("border_mode", "valid"))
        if padding not in ("valid", "same"):
            raise KerasImportError(f"unsupported Conv2D padding '{padding}'")
        lc = ConvolutionLayer(
            name=name, n_out=n_out, kernel_size=kernel, stride=stride,
            convolution_mode="same" if padding == "same" else "truncate",
            activation=_act(conf.get("activation")),
            has_bias=conf.get("use_bias", conf.get("bias", True)))

        def copy(w):
            kernel_w = w.get("kernel", w.get("W"))
            if kernel_w is not None and kernel_w.ndim != 4:
                raise KerasImportError("Conv2D kernel must be 4-D (HWIO)")
            out = {"W": kernel_w}  # TF HWIO == our [kh,kw,in,out]
            if lc.has_bias:
                out["b"] = w.get("bias", w.get("b"))
            return out

        return _LayerMap(lc, copy)
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        kernel = _pair(conf.get("pool_size", (2, 2)))
        stride = _pair(conf.get("strides") or conf.get("pool_size", (2, 2)))
        return _LayerMap(SubsamplingLayer(
            name=name, kernel_size=kernel, stride=stride,
            pooling_type="max" if cls.startswith("Max") else "avg"),
            lambda w: {})
    if cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D",
               "GlobalMaxPooling2D", "GlobalMaxPooling1D"):
        return _LayerMap(GlobalPoolingLayer(
            name=name, pooling_type="max" if "Max" in cls else "avg"),
            lambda w: {})
    if cls in ("MaxPooling1D", "AveragePooling1D"):
        from ..nn.layers.convolution import Subsampling1DLayer
        k = conf.get("pool_size", conf.get("pool_length", 2))
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = conf.get("strides", conf.get("stride")) or k
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        return _LayerMap(Subsampling1DLayer(
            name=name, kernel_size=k, stride=s,
            pooling_type="max" if cls.startswith("Max") else "avg"),
            lambda w: {})
    if cls in ("Conv1D", "Convolution1D"):
        from ..nn.layers.convolution import Convolution1DLayer
        n_out = int(conf.get("filters", conf.get("nb_filter", 0)))
        k = conf.get("kernel_size", conf.get("filter_length", 3))
        k = int(k[0] if isinstance(k, (list, tuple)) else k)
        s = conf.get("strides", conf.get("subsample_length", 1))
        s = int(s[0] if isinstance(s, (list, tuple)) else s)
        padding = conf.get("padding", conf.get("border_mode", "valid"))
        if padding not in ("valid", "same"):
            # 'causal' pads left-only — silently mapping it to 'same'
            # would leak future timesteps
            raise KerasImportError(f"unsupported Conv1D padding '{padding}'")
        lc = Convolution1DLayer(
            name=name, n_out=n_out, kernel_size=k, stride=s,
            convolution_mode="same" if padding == "same" else "truncate",
            activation=_act(conf.get("activation")),
            has_bias=conf.get("use_bias", conf.get("bias", True)))

        def copy(w):
            out = {"W": w.get("kernel", w.get("W"))}  # [k, in, out]
            if lc.has_bias:
                out["b"] = w.get("bias", w.get("b"))
            return out

        return _LayerMap(lc, copy)
    if cls == "ZeroPadding2D":
        from ..nn.layers.convolution import ZeroPaddingLayer
        pad = conf.get("padding", 1)
        if isinstance(pad, int):
            padding = (pad, pad, pad, pad)
        elif len(pad) == 2 and all(isinstance(p, int) for p in pad):
            padding = (pad[0], pad[0], pad[1], pad[1])
        else:  # [[top, bottom], [left, right]]
            padding = (pad[0][0], pad[0][1], pad[1][0], pad[1][1])
        return _LayerMap(ZeroPaddingLayer(name=name, padding=padding),
                         lambda w: {})
    if cls == "UpSampling2D":
        from ..nn.layers.convolution import Upsampling2D
        size = _pair(conf.get("size", (2, 2)))
        return _LayerMap(Upsampling2D(name=name, size=size), lambda w: {})
    if cls == "BatchNormalization":
        eps = float(conf.get("epsilon", 1e-3))
        momentum = float(conf.get("momentum", 0.99))
        lc = BatchNormalization(name=name, eps=eps, decay=momentum)

        def copy(w):
            out = {}
            if "gamma" in w:
                out["gamma"] = w["gamma"]
            if "beta" in w:
                out["beta"] = w["beta"]
            # moving stats go to state, handled by caller via special keys
            out["__state__"] = {
                "mean": w.get("moving_mean", w.get("running_mean")),
                "var": w.get("moving_variance", w.get("running_std")),
            }
            return out

        return _LayerMap(lc, copy)
    if cls == "LSTM":
        n_out = int(conf.get("units", conf.get("output_dim", 0)))
        act = _act(conf.get("activation", "tanh"))
        rec_act = conf.get("recurrent_activation",
                           conf.get("inner_activation", "hard_sigmoid"))
        lc = LSTM(name=name, n_out=n_out, activation=act,
                  gate_activation=_act(rec_act))
        if not conf.get("return_sequences", True):
            # Keras return_sequences=False keeps only the final step; the
            # reference maps this with the LastTimeStep wrapper
            from ..nn.layers.recurrent import LastTimeStep
            lc = LastTimeStep(name=name, underlying=lc)

        def copy(w):
            if "kernel" in w:  # Keras 2: fused [in,4h] with gate order ifco
                k, rk, b = w["kernel"], w["recurrent_kernel"], w.get("bias")
            else:  # Keras 1: per-gate matrices
                k = np.concatenate([w["W_i"], w["W_f"], w["W_c"], w["W_o"]], 1)
                rk = np.concatenate([w["U_i"], w["U_f"], w["U_c"], w["U_o"]], 1)
                b = np.concatenate([w["b_i"], w["b_f"], w["b_c"], w["b_o"]])
            h = n_out

            def reorder(m):  # keras i,f,c,o -> ours i,f,o,g(=c)
                blocks = [m[..., i * h:(i + 1) * h] for i in range(4)]
                return np.concatenate(
                    [blocks[0], blocks[1], blocks[3], blocks[2]], axis=-1)

            out = {"W": reorder(k), "U": reorder(rk)}
            out["b"] = (reorder(b.reshape(1, -1)).reshape(-1)
                        if b is not None else np.zeros(4 * h, np.float32))
            return out

        return _LayerMap(lc, copy)
    if cls == "SimpleRNN":
        n_out = int(conf.get("units", conf.get("output_dim", 0)))
        lc = SimpleRnn(name=name, n_out=n_out,
                       activation=_act(conf.get("activation", "tanh")))
        if not conf.get("return_sequences", True):
            from ..nn.layers.recurrent import LastTimeStep
            lc = LastTimeStep(name=name, underlying=lc)

        def copy(w):
            out = {"W": w.get("kernel", w.get("W")),
                   "U": w.get("recurrent_kernel", w.get("U"))}
            b = w.get("bias", w.get("b"))
            out["b"] = b if b is not None else np.zeros(n_out, np.float32)
            return out

        return _LayerMap(lc, copy)
    if cls == "Embedding":
        n_out = int(conf.get("output_dim"))
        n_in = int(conf.get("input_dim"))
        lc = EmbeddingLayer(name=name, n_in=n_in, n_out=n_out,
                            activation="identity")
        return _LayerMap(lc, lambda w: {
            "W": w.get("embeddings", w.get("W"))})
    raise KerasImportError(f"unsupported Keras layer class '{cls}' "
                           "(reference KerasLayer registry)")


def _layer_weight_groups(f: Hdf5File) -> Dict[str, Dict[str, np.ndarray]]:
    """{layer_name: {short_weight_name: array}} from /model_weights (or the
    root for weights-only files)."""
    root = f["model_weights"] if "model_weights" in f.keys() else f
    out: Dict[str, Dict[str, np.ndarray]] = {}
    names = root.attrs.get("layer_names")
    layer_names = ([n.decode() if isinstance(n, bytes) else n
                    for n in list(names)]
                   if names is not None else root.keys())
    for lname in layer_names:
        try:
            g = root[lname]
        except KeyError:      # weightless layer with no group written
            out[lname] = {}
            continue
        weights: Dict[str, np.ndarray] = {}
        wnames = g.attrs.get("weight_names")
        wlist = list(wnames) if wnames is not None else g.keys()
        for wn in wlist:
            if isinstance(wn, bytes):
                wn = wn.decode()
            try:  # Keras nests an inner scope group (layer/layer/kernel:0)…
                ds = g[wn]
            except KeyError:  # …weights-only layouts store datasets flat
                ds = g[wn.split("/")[-1]]
            short = wn.split("/")[-1].split(":")[0]
            # Keras 1 style "dense_1_W" -> "W"
            if short.startswith(lname + "_"):
                short = short[len(lname) + 1:]
            weights[short] = ds.read()
        out[lname] = weights
    return out


def import_keras_sequential_model(path_or_bytes) -> MultiLayerNetwork:
    """Load a Keras Sequential ``model.save()`` file into a
    MultiLayerNetwork (reference
    ``KerasModelImport.importKerasSequentialModelAndWeights``)."""
    f = Hdf5File(path_or_bytes)
    raw = f.attrs.get("model_config")
    if raw is None:
        raise KerasImportError("no model_config attribute — is this a "
                               "weights-only file? (use layer_weight_groups)")
    config = json.loads(raw if isinstance(raw, str) else str(raw))
    if config.get("class_name") != "Sequential":
        raise KerasImportError(
            f"not a Sequential model ({config.get('class_name')}); "
            "functional-graph import is not yet supported")
    layer_list = config["config"]
    if isinstance(layer_list, dict):  # Keras 2.2+: {"name":..,"layers":[..]}
        layer_list = layer_list["layers"]

    itype = None
    maps: List[_LayerMap] = []
    mapped_names: List[str] = []
    # find the last REAL layer (Flatten/InputLayer don't count)
    real_idx = [i for i, l in enumerate(layer_list)
                if l["class_name"] not in ("Flatten", "InputLayer")]
    rnn_ctx = False   # does the running activation carry a time axis?
    for i, l in enumerate(layer_list):
        cls = l["class_name"]
        conf = _cfg(l)
        if itype is None:
            it = _input_type_from(conf)
            if it is not None:
                itype = it
                rnn_ctx = it.kind == "rnn"
        if cls == "InputLayer":
            continue
        lm = _map_layer(cls, conf, is_last=(real_idx and i == real_idx[-1]),
                        rnn_input=rnn_ctx)
        if cls in ("LSTM", "SimpleRNN", "Conv1D", "Convolution1D"):
            rnn_ctx = conf.get("return_sequences", True) or \
                cls in ("Conv1D", "Convolution1D")
        elif cls == "Reshape":
            rnn_ctx = len(conf.get("target_shape", ())) == 2
        elif cls in ("RepeatVector", "TimeDistributed"):
            rnn_ctx = True
        elif cls not in ("Dropout", "Activation", "MaxPooling1D",
                         "AveragePooling1D", "BatchNormalization",
                         "LeakyReLU", "ELU", "ThresholdedReLU", "Permute"):
            rnn_ctx = rnn_ctx and cls == "Dense"  # time-distributed keeps t
        if lm.conf is None:  # Flatten
            continue
        maps.append(lm)
        mapped_names.append(conf.get("name") or f"layer_{i}")
    if itype is None:
        raise KerasImportError("no batch_input_shape on the first layer")

    builder = (NeuralNetConfiguration.builder()
               .seed(12345)
               .updater(Sgd(learning_rate=0.01))
               .list())
    for lm in maps:
        builder.layer(lm.conf)
    conf = builder.set_input_type(itype).build()
    net = MultiLayerNetwork(conf).init()

    groups = _layer_weight_groups(f)
    _copy_weights_into(groups, [
        (lname, lm.copy, net.params.get(f"layer_{i}", {}),
         net.state.setdefault(f"layer_{i}", {}))
        for i, (lm, lname) in enumerate(zip(maps, mapped_names))])
    # re-materialize as jax arrays
    import jax.numpy as jnp
    import jax
    net.params = jax.tree_util.tree_map(jnp.asarray, net.params)
    net.state = jax.tree_util.tree_map(jnp.asarray, net.state)
    return net


def _copy_weights_into(groups, items) -> None:
    """Shared weight-copy loop.  items: (keras_name, copy_fn, target_params,
    target_state) per mapped layer."""
    for lname, copy_fn, target, st in items:
        if copy_fn is None:
            continue
        params = copy_fn(groups.get(lname, {}))
        state_extra = params.pop("__state__", None)
        for pname, val in params.items():
            if val is None:
                raise KerasImportError(
                    f"layer {lname}: weight '{pname}' not found in the "
                    "HDF5 file (layer group missing or dataset names "
                    "unrecognized)")
            val = np.asarray(val, np.float32)
            if pname not in target:
                raise KerasImportError(
                    f"layer {lname}: param '{pname}' missing on our side")
            if tuple(target[pname].shape) != tuple(val.shape):
                raise KerasImportError(
                    f"layer {lname}: shape mismatch for '{pname}': "
                    f"keras {val.shape} vs ours {tuple(target[pname].shape)}")
            target[pname] = val
        if state_extra and st is not None:
            if state_extra.get("mean") is not None:
                st["mean"] = np.asarray(state_extra["mean"], np.float32)
            if state_extra.get("var") is not None:
                st["var"] = np.asarray(state_extra["var"], np.float32)


# Keras merge-layer class -> our graph vertex
_MERGE_ELEMENTWISE = {"Add": "add", "Subtract": "subtract",
                      "Multiply": "product", "Average": "average",
                      "Maximum": "max"}
# Keras 1 Merge(mode=...) -> op
_MERGE_MODE = {"sum": "add", "mul": "product", "ave": "average",
               "max": "max", "concat": None}


def _inbound_names(layer: Dict[str, Any]) -> List[str]:
    """First inbound node's source layer names (Keras 1 and 2 formats)."""
    nodes = layer.get("inbound_nodes") or []
    if not nodes:
        return []
    node = nodes[0]
    if isinstance(node, dict):  # Keras 3-style {"args": ...} unsupported
        raise KerasImportError("unsupported inbound_nodes format (Keras 3)")
    return [entry[0] for entry in node]


def import_keras_model(path_or_bytes):
    """Load a Keras functional ``Model`` save file into a ComputationGraph
    (reference ``KerasModelImport.importKerasModelAndWeights`` →
    ``KerasModel.java`` building a CG).  Sequential files are delegated to
    :func:`import_keras_sequential_model`."""
    from ..nn.conf.computation_graph import (ElementWiseVertex, GraphBuilder,
                                             MergeVertex)
    from ..nn.computation_graph import ComputationGraph

    f = Hdf5File(path_or_bytes)
    raw = f.attrs.get("model_config")
    if raw is None:
        raise KerasImportError("no model_config attribute in the file")
    config = json.loads(raw if isinstance(raw, str) else str(raw))
    cls_name = config.get("class_name")
    if cls_name == "Sequential":
        return import_keras_sequential_model(path_or_bytes)
    if cls_name not in ("Model", "Functional"):
        raise KerasImportError(f"unsupported model class '{cls_name}'")
    cfg = config["config"]
    layers = cfg["layers"]
    out_names = [o[0] for o in cfg["output_layers"]]

    g = GraphBuilder(defaults={"updater": Sgd(learning_rate=0.01)})
    alias: Dict[str, str] = {}      # skipped layers forward to their input
    copy_items: List[Tuple[str, Any]] = []
    input_types: List[InputType] = []
    rnn_of: Dict[str, bool] = {}    # layer name -> carries a time axis

    def resolve(names: List[str]) -> List[str]:
        return [alias.get(n, n) for n in names]

    for l in layers:
        cls = l["class_name"]
        conf = _cfg(l)
        name = l.get("name") or conf.get("name")
        raw_inbound = _inbound_names(l)
        inbound = resolve(raw_inbound)
        rnn_in = any(rnn_of.get(n, False) for n in raw_inbound)
        if cls == "InputLayer" or not inbound:
            it = _input_type_from(conf)
            if it is None:
                raise KerasImportError(
                    f"input layer '{name}' has no batch_input_shape")
            g.add_inputs(name)
            input_types.append(it)
            rnn_of[name] = it.kind == "rnn"
            continue
        if cls in _MERGE_ELEMENTWISE:
            g.add_vertex(name, ElementWiseVertex(op=_MERGE_ELEMENTWISE[cls]),
                         *inbound)
            rnn_of[name] = rnn_in
            continue
        if cls in ("Concatenate", "Merge"):
            mode = conf.get("mode", "concat")
            if cls == "Concatenate" or _MERGE_MODE.get(mode) is None:
                g.add_vertex(name, MergeVertex(), *inbound)
            else:
                g.add_vertex(name, ElementWiseVertex(op=_MERGE_MODE[mode]),
                             *inbound)
            rnn_of[name] = rnn_in
            continue
        # time-axis propagation (mirrors the Sequential path's rnn_ctx)
        if cls in ("LSTM", "SimpleRNN", "Conv1D", "Convolution1D"):
            rnn_of[name] = conf.get("return_sequences", True) or \
                cls in ("Conv1D", "Convolution1D")
        elif cls == "Reshape":
            rnn_of[name] = len(conf.get("target_shape", ())) == 2
        elif cls in ("RepeatVector", "TimeDistributed"):
            rnn_of[name] = True
        elif cls in ("Dropout", "Activation", "MaxPooling1D",
                     "AveragePooling1D", "BatchNormalization", "Dense",
                     "LeakyReLU", "ELU", "ThresholdedReLU", "Permute"):
            rnn_of[name] = rnn_in
        else:
            rnn_of[name] = False
        lm = _map_layer(cls, conf, is_last=name in out_names,
                        rnn_input=rnn_in)
        if lm.conf is None:  # Flatten: auto preprocessor handles reshapes
            alias[name] = inbound[0]
            continue
        g.add_layer(name, lm.conf, *inbound)
        copy_items.append((name, lm.copy))

    conf_built = (g.set_outputs(*resolve(out_names))
                  .set_input_types(*input_types).build())
    net = ComputationGraph(conf_built).init()
    groups = _layer_weight_groups(f)
    _copy_weights_into(groups, [
        (lname, copy_fn, net.params.get(lname, {}),
         net.state.setdefault(lname, {}))
        for lname, copy_fn in copy_items])
    import jax
    import jax.numpy as jnp
    net.params = jax.tree_util.tree_map(jnp.asarray, net.params)
    net.state = jax.tree_util.tree_map(jnp.asarray, net.state)
    return net


class KerasModelImport:
    """Entry points (reference ``KerasModelImport.java:50-157``)."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path) -> MultiLayerNetwork:
        return import_keras_sequential_model(path)

    @staticmethod
    def import_keras_model_and_weights(path):
        """Functional (or Sequential) model → ComputationGraph (or MLN)."""
        return import_keras_model(path)
