"""Pretrained-model helpers: VGG16 preprocessing + ImageNet decoding.

Reference ``deeplearning4j-modelimport/.../trainedmodels/`` —
``TrainedModels.java`` (VGG16 / VGG16NOTOP enum with input preprocessing
and prediction decoding) + ``util/imagenet_class_index``-style label table.
This environment has no egress, so weights come from a user-supplied Keras
HDF5 file (loaded through our importer) and labels from
``IMAGENET_LABELS`` (one label per line, 1000 lines) with a ``class_<i>``
fallback — decoding logic and preprocessing are fully functional either way.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TrainedModels", "VGG16Helper", "ImageNetLabels"]

# caffe-style channel means the VGG family was trained with (RGB order)
VGG_MEAN_RGB = (123.68, 116.779, 103.939)


class ImageNetLabels:
    """1000-class label table (reference fetches a JSON index at runtime;
    here: ``IMAGENET_LABELS`` file or positional fallback names)."""

    def __init__(self, path: Optional[str] = None):
        path = path or os.environ.get("IMAGENET_LABELS")
        self._labels: List[str]
        if path and Path(path).expanduser().exists():
            lines = Path(path).expanduser().read_text(
                encoding="utf-8").splitlines()
            self._labels = [l.strip() for l in lines if l.strip()]
        else:
            self._labels = [f"class_{i}" for i in range(1000)]

    def get_label(self, idx: int) -> str:
        return self._labels[idx]

    def __len__(self) -> int:
        return len(self._labels)

    def decode_predictions(self, probs, top: int = 5
                           ) -> List[List[Tuple[str, float]]]:
        """[b, 1000] probabilities → per-example [(label, prob)] top-k
        (reference ``TrainedModels.VGG16.decodePredictions``)."""
        p = np.asarray(probs)
        if p.ndim == 1:
            p = p[None]
        out = []
        for row in p:
            idx = np.argsort(-row)[:top]
            out.append([(self.get_label(int(i)), float(row[i]))
                        for i in idx])
        return out


class VGG16Helper:
    """Preprocess + predict + decode for VGG16 (reference
    ``TrainedModels.VGG16``)."""

    input_shape = (224, 224, 3)

    def __init__(self, labels: Optional[ImageNetLabels] = None):
        self.labels = labels or ImageNetLabels()

    @staticmethod
    def preprocess(images) -> np.ndarray:
        """NHWC RGB uint8/float [0,255] → mean-subtracted float32 (the
        caffe-style preprocessing VGG16 was trained with)."""
        x = np.asarray(images, np.float32)
        if x.ndim == 3:
            x = x[None]
        if x.max() <= 1.0 + 1e-6:
            x = x * 255.0
        return x - np.asarray(VGG_MEAN_RGB, np.float32)

    def build_network(self, weights_path: Optional[str] = None):
        """Fresh zoo VGG16, optionally loading Keras HDF5 weights through
        the importer (no-egress stand-in for the reference's checksummed
        download, ``ZooModel.java:40-81``)."""
        if weights_path:
            from .keras import import_keras_model
            return import_keras_model(weights_path)
        from ..models.zoo import VGG16
        return VGG16().init()

    def predict_and_decode(self, net, images, top: int = 5):
        probs = net.output(self.preprocess(images))
        if isinstance(probs, (list, tuple)):
            probs = probs[0]
        return self.labels.decode_predictions(np.asarray(probs), top=top)


class TrainedModels:
    """Enum-style access (reference ``TrainedModels.java``)."""
    VGG16 = VGG16Helper()
