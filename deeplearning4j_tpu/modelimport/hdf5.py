"""Minimal pure-Python read-only HDF5 parser.

Replaces the reference's JavaCPP→libhdf5 binding
(``deeplearning4j-modelimport/.../Hdf5Archive.java:25,46``) — this
environment has no h5py, and the subset Keras 1.x/2.x HDF5 files actually
use is small: superblock v0/v2, v1 ("old-style") object headers with
symbol-table groups (libhdf5 default unless libver='latest'), contiguous or
chunked(+gzip/shuffle) datasets of fixed-point/float data, and attributes
holding fixed or variable-length strings (vlen via global heap collections).

Layout references: the HDF5 File Format Specification v2/v3 (public).
Unsupported features (fractal-heap "new-style" groups, v4 layouts, szip)
raise ``Hdf5FormatError`` with the feature name rather than misparsing.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["Hdf5File", "Hdf5Group", "Hdf5Dataset", "Hdf5FormatError"]

_SIG = b"\x89HDF\r\n\x1a\n"
UNDEF = 0xFFFFFFFFFFFFFFFF


class Hdf5FormatError(ValueError):
    pass


def _u(data: bytes, off: int, n: int) -> int:
    return int.from_bytes(data[off:off + n], "little")


class _Datatype:
    def __init__(self, cls: int, size: int, raw: bytes):
        self.cls = cls          # 0 fixed, 1 float, 3 string, 9 vlen
        self.size = size
        self.raw = raw
        self.signed = True
        self.vlen_string = False
        self.base: Optional["_Datatype"] = None

    @property
    def numpy_dtype(self):
        if self.cls == 0:
            return np.dtype(f"{'i' if self.signed else 'u'}{self.size}")
        if self.cls == 1:
            return np.dtype(f"f{self.size}")
        if self.cls == 3:
            return np.dtype(f"S{self.size}")
        raise Hdf5FormatError(f"unsupported datatype class {self.cls}")


def _parse_datatype(body: bytes) -> _Datatype:
    b0 = body[0]
    cls = b0 & 0x0F
    bits0 = body[1]
    size = _u(body, 4, 4)
    dt = _Datatype(cls, size, body)
    if cls == 0:
        dt.signed = bool(bits0 & 0x08)
    elif cls == 9:
        # vlen: bits0 low nibble: 0 sequence, 1 string
        dt.vlen_string = (bits0 & 0x0F) == 1
        dt.base = _parse_datatype(body[8:])
    return dt


class _Dataspace:
    def __init__(self, dims: Tuple[int, ...]):
        self.dims = dims

    @property
    def n_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n


def _parse_dataspace(body: bytes) -> _Dataspace:
    ver = body[0]
    ndims = body[1]
    flags = body[2]
    if ver == 1:
        off = 8
    elif ver == 2:
        off = 4
    else:
        raise Hdf5FormatError(f"dataspace version {ver}")
    dims = tuple(_u(body, off + 8 * i, 8) for i in range(ndims))
    return _Dataspace(dims)


class _Filter:
    def __init__(self, fid: int, client: List[int]):
        self.id = fid
        self.client = client


def _parse_filters(body: bytes) -> List[_Filter]:
    ver = body[0]
    nf = body[1]
    filters = []
    if ver == 1:
        off = 8
    elif ver == 2:
        off = 2
    else:
        raise Hdf5FormatError(f"filter pipeline version {ver}")
    for _ in range(nf):
        fid = _u(body, off, 2)
        name_len = _u(body, off + 2, 2)
        ncv = _u(body, off + 6, 2)
        off += 8
        if ver == 1 or fid >= 256:
            nl = name_len + (-name_len) % 8 if ver == 1 else name_len
            off += nl
        cvals = [_u(body, off + 4 * i, 4) for i in range(ncv)]
        off += 4 * ncv
        if ver == 1 and ncv % 2 == 1:
            off += 4
        filters.append(_Filter(fid, cvals))
    return filters


class _Layout:
    def __init__(self):
        self.kind = None          # 'contiguous' | 'chunked' | 'compact'
        self.address = UNDEF
        self.size = 0
        self.chunk_dims: Tuple[int, ...] = ()
        self.elem_size = 0
        self.compact_data = b""
        self.chunk_index = 0      # 0 = v1 btree; v4: 1 single, 2 implicit,
        self.single_size = 0      # 3 fixed array (5 = v2 btree unsupported)
        self.single_mask = 0


def _parse_layout(body: bytes) -> _Layout:
    ver = body[0]
    lay = _Layout()
    if ver == 3:
        cls = body[1]
        if cls == 0:
            size = _u(body, 2, 2)
            lay.kind = "compact"
            lay.compact_data = body[4:4 + size]
        elif cls == 1:
            lay.kind = "contiguous"
            lay.address = _u(body, 2, 8)
            lay.size = _u(body, 10, 8)
        elif cls == 2:
            ndims = body[2]
            lay.kind = "chunked"
            lay.address = _u(body, 3, 8)
            lay.chunk_dims = tuple(_u(body, 11 + 4 * i, 4)
                                   for i in range(ndims - 1))
            lay.elem_size = _u(body, 11 + 4 * (ndims - 1), 4)
        else:
            raise Hdf5FormatError(f"layout class {cls}")
    elif ver in (1, 2):
        ndims = body[1]
        cls = body[2]
        if cls == 1:
            lay.kind = "contiguous"
            lay.address = _u(body, 8, 8)
        elif cls == 2:
            lay.kind = "chunked"
            lay.address = _u(body, 8, 8)
            dims = [_u(body, 16 + 4 * i, 4) for i in range(ndims)]
            lay.chunk_dims = tuple(dims[:-1])
            lay.elem_size = dims[-1]
        else:
            raise Hdf5FormatError(f"layout v1 class {cls}")
    elif ver == 4:
        cls = body[1]
        if cls == 0:
            size = _u(body, 2, 2)
            lay.kind = "compact"
            lay.compact_data = body[4:4 + size]
        elif cls == 1:
            lay.kind = "contiguous"
            lay.address = _u(body, 2, 8)
            lay.size = _u(body, 10, 8)
        elif cls == 2:
            flags = body[2]
            ndims = body[3]
            enc = body[4]
            off = 5
            lay.kind = "chunked"
            # like v3, dimensionality = rank + 1 with element size last
            dims = tuple(_u(body, off + enc * i, enc) for i in range(ndims))
            lay.chunk_dims = dims[:-1]
            lay.elem_size = dims[-1]
            off += enc * ndims
            itype = body[off]
            off += 1
            lay.chunk_index = itype
            if itype == 1:      # single chunk
                if flags & 0x2:  # filtered: explicit size + mask
                    lay.single_size = _u(body, off, 8)
                    lay.single_mask = _u(body, off + 8, 4)
                    off += 12
            elif itype == 2:    # implicit (contiguous chunk array)
                pass
            elif itype == 3:    # fixed array
                off += 1        # page bits (re-read from the FAHD header)
            elif itype == 4:    # extensible array params
                off += 6
            elif itype == 5:    # v2 btree params
                off += 6
            else:
                raise Hdf5FormatError(f"chunk index type {itype}")
            lay.address = _u(body, off, 8)
        else:
            raise Hdf5FormatError(f"layout v4 class {cls}")
    else:
        raise Hdf5FormatError(f"layout version {ver} not supported")
    return lay


class _Message:
    def __init__(self, mtype: int, body: bytes):
        self.type = mtype
        self.body = body


class Hdf5Dataset:
    def __init__(self, f: "Hdf5File", name: str, dtype: _Datatype,
                 space: _Dataspace, layout: _Layout,
                 filters: List[_Filter], attrs: Dict[str, Any]):
        self._f = f
        self.name = name
        self.dtype = dtype
        self.shape = space.dims
        self._layout = layout
        self._filters = filters
        self.attrs = attrs

    def __getitem__(self, key) -> np.ndarray:
        return self.read()[key]

    def read(self) -> np.ndarray:
        dt = self.dtype
        if dt.cls == 9:
            return self._read_vlen()
        npdt = dt.numpy_dtype
        raw = self._raw_bytes(npdt.itemsize)
        n = 1
        for d in self.shape:
            n *= d
        arr = np.frombuffer(raw[:n * npdt.itemsize], dtype=npdt)
        return arr.reshape(self.shape) if self.shape else arr.reshape(())

    def _read_vlen(self) -> np.ndarray:
        if not self.dtype.vlen_string:
            raise Hdf5FormatError("vlen non-string dataset")
        raw = self._raw_bytes(16)
        n = 1
        for d in self.shape:
            n *= d
        out = [self._f._read_gheap_object(raw, i * 16) for i in range(n)]
        arr = np.asarray(out, dtype=object)
        return arr.reshape(self.shape) if self.shape else arr.reshape(())

    def _raw_bytes(self, elem_size: int) -> bytes:
        lay = self._layout
        if lay.kind == "compact":
            return lay.compact_data
        if lay.kind == "contiguous":
            if lay.address == UNDEF:
                return b"\x00" * (self._n_elems() * elem_size)
            total = self._n_elems() * elem_size
            return self._f.data[lay.address:lay.address + total]
        if lay.kind == "chunked":
            return self._read_chunked(elem_size)
        raise Hdf5FormatError(f"layout {lay.kind}")

    def _n_elems(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def _apply_filters(self, raw: bytes, mask: int) -> bytes:
        for i, flt in enumerate(reversed(self._filters)):
            pos = len(self._filters) - 1 - i
            if mask & (1 << pos):
                continue
            if flt.id == 1:        # gzip
                raw = zlib.decompress(raw)
            elif flt.id == 2:      # shuffle
                es = flt.client[0] if flt.client else 4
                n = len(raw) // es
                arr = np.frombuffer(raw[:n * es], np.uint8).reshape(es, n)
                raw = arr.T.tobytes() + raw[n * es:]
            elif flt.id == 3:      # fletcher32: strip trailing checksum
                raw = raw[:-4]
            else:
                raise Hdf5FormatError(f"filter id {flt.id}")
        return raw

    def _read_chunked(self, elem_size: int) -> bytes:
        lay = self._layout
        ndims = len(self.shape)
        full = np.zeros(self._n_elems() * elem_size, np.uint8)
        view = full.reshape(self.shape + (elem_size,)) if ndims else full
        if lay.chunk_index:
            nbytes = int(np.prod(lay.chunk_dims)) * elem_size if ndims else \
                elem_size
            chunks = self._f._iter_chunks_v4(lay, self.shape, nbytes)
        else:
            chunks = self._f._iter_chunks(lay.address, ndims)
        for (offsets, size, mask, addr) in chunks:
            raw = self._f.data[addr:addr + size]
            raw = self._apply_filters(raw, mask)
            cdims = lay.chunk_dims
            carr = np.frombuffer(
                raw[: int(np.prod(cdims)) * elem_size], np.uint8
            ).reshape(tuple(cdims) + (elem_size,))
            # clip chunk to the dataset bounds
            slices = tuple(
                slice(offsets[d], min(offsets[d] + cdims[d], self.shape[d]))
                for d in range(ndims))
            csl = tuple(slice(0, s.stop - s.start) for s in slices)
            view[slices] = carr[csl]
        return full.tobytes()


class Hdf5Group:
    def __init__(self, f: "Hdf5File", name: str):
        self._f = f
        self.name = name
        self.attrs: Dict[str, Any] = {}
        self._children: Dict[str, int] = {}   # name -> object header addr

    def keys(self) -> List[str]:
        return list(self._children)

    def __contains__(self, name: str) -> bool:
        return name in self._children or name.split("/")[0] in self._children

    def __getitem__(self, path: str):
        parts = [p for p in path.split("/") if p]
        node: Any = self
        for p in parts:
            if not isinstance(node, Hdf5Group) or p not in node._children:
                raise KeyError(f"{p!r} not in group {node.name!r}")
            node = self._f._load_object(node._children[p],
                                        f"{node.name.rstrip('/')}/{p}")
        return node

    def items(self):
        return [(k, self[k]) for k in self.keys()]


class Hdf5File(Hdf5Group):
    """Read-only HDF5 file over an in-memory byte buffer."""

    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray)):
            self.data = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as fh:
                self.data = fh.read()
        super().__init__(self, "/")
        self._cache: Dict[int, Any] = {}
        root_addr = self._parse_superblock()
        root = self._load_object(root_addr, "/")
        self._children = root._children
        self.attrs = root.attrs

    # -------------------------------------------------------------- plumbing
    def _parse_superblock(self) -> int:
        if self.data[:8] != _SIG:
            raise Hdf5FormatError("not an HDF5 file (bad signature)")
        ver = self.data[8]
        if ver == 0:
            so, sl = self.data[13], self.data[14]
            if (so, sl) != (8, 8):
                raise Hdf5FormatError("only 8-byte offsets/lengths supported")
            # 24B fixed part, 4 file addresses (base/freespace/eof/driver),
            # then the root symbol-table entry: name off(8) + OH addr(8)
            return _u(self.data, 24 + 32 + 8, 8)
        if ver in (2, 3):
            so = self.data[9]
            if so != 8:
                raise Hdf5FormatError("only 8-byte offsets supported")
            return _u(self.data, 12 + 8 * 3, 8)
        raise Hdf5FormatError(f"superblock version {ver}")

    # ---- object headers ---------------------------------------------------
    def _read_messages_v1(self, addr: int) -> List[_Message]:
        d = self.data
        nmsgs = _u(d, addr + 2, 2)
        hdr_size = _u(d, addr + 8, 4)
        blocks = [(addr + 16, hdr_size)]
        msgs: List[_Message] = []
        while blocks and len(msgs) < nmsgs:
            off, remaining = blocks.pop(0)
            while remaining >= 8 and len(msgs) < nmsgs:
                mtype = _u(d, off, 2)
                size = _u(d, off + 2, 2)
                body = d[off + 8:off + 8 + size]
                if mtype == 0x0010:  # continuation
                    blocks.append((_u(body, 0, 8), _u(body, 8, 8)))
                else:
                    msgs.append(_Message(mtype, body))
                off += 8 + size
                remaining -= 8 + size
        return msgs

    def _read_messages_v2(self, addr: int) -> List[_Message]:
        d = self.data
        if d[addr:addr + 4] != b"OHDR":
            raise Hdf5FormatError("bad v2 object header signature")
        flags = d[addr + 5]
        off = addr + 6
        if flags & 0x20:
            off += 16  # times
        if flags & 0x10:
            off += 4   # max compact/dense
        size_bytes = 1 << (flags & 0x3)
        chunk_size = _u(d, off, size_bytes)
        off += size_bytes
        msgs: List[_Message] = []
        # chunk-0 size covers the messages + gap but not prefix/checksum;
        # continuation length covers OCHK signature + messages + checksum.
        # blocks carry (start, end-of-message-region) with both excluded.
        blocks = [(off, off + chunk_size)]
        creation_tracked = bool(flags & 0x04)
        hdr = 6 if creation_tracked else 4
        while blocks:
            p, end = blocks.pop(0)
            while p + hdr <= end:
                mtype = d[p]
                size = _u(d, p + 1, 2)
                p += hdr
                body = d[p:p + size]
                if mtype == 0x10:
                    caddr, clen = _u(body, 0, 8), _u(body, 8, 8)
                    blocks.append((caddr + 4, caddr + clen - 4))
                else:
                    msgs.append(_Message(mtype, body))
                p += size
        return msgs

    def _load_object(self, addr: int, name: str):
        if addr in self._cache:
            return self._cache[addr]
        d = self.data
        if d[addr:addr + 4] == b"OHDR":
            msgs = self._read_messages_v2(addr)
        else:
            msgs = self._read_messages_v1(addr)
        attrs: Dict[str, Any] = {}
        dtype = space = layout = None
        filters: List[_Filter] = []
        children: Dict[str, int] = {}
        is_group = False
        for m in msgs:
            if m.type == 0x0001:
                space = _parse_dataspace(m.body)
            elif m.type == 0x0003:
                dtype = _parse_datatype(m.body)
            elif m.type == 0x0008:
                layout = _parse_layout(m.body)
            elif m.type == 0x000B:
                filters = _parse_filters(m.body)
            elif m.type == 0x000C:
                k, v = self._parse_attribute(m.body)
                attrs[k] = v
            elif m.type == 0x0011:  # symbol table (old-style group)
                is_group = True
                btree, heap = _u(m.body, 0, 8), _u(m.body, 8, 8)
                children.update(self._read_group_btree(btree, heap))
            elif m.type == 0x0006:  # link message (new-style compact group)
                is_group = True
                lname, laddr = self._parse_link(m.body)
                children[lname] = laddr
            elif m.type == 0x0015:  # attribute info: dense attrs unsupported
                ai_flags = m.body[1] if len(m.body) >= 2 else 0
                pos = 2 + (2 if ai_flags & 0x1 else 0)
                afheap = (_u(m.body, pos, 8)
                          if len(m.body) >= pos + 8 else UNDEF)
                if afheap != UNDEF:
                    raise Hdf5FormatError(
                        "dense attribute storage (fractal heap) unsupported")
            elif m.type == 0x0002:  # link info: dense storage unsupported
                # body: version(1) flags(1) [max creation index(8) if
                # flags&1] fractal-heap addr(8) name-index btree(8) …
                li_flags = m.body[1] if len(m.body) >= 2 else 0
                pos = 2 + (8 if li_flags & 0x1 else 0)
                fheap = (_u(m.body, pos, 8)
                         if len(m.body) >= pos + 8 else UNDEF)
                # only reject if links actually live in a fractal heap
                if fheap != UNDEF:
                    raise Hdf5FormatError(
                        "new-style dense groups (fractal heap) unsupported — "
                        "write the file with libver='earliest'")
        if is_group or (dtype is None and layout is None):
            g = Hdf5Group(self, name)
            g.attrs = attrs
            g._children = children
            self._cache[addr] = g
            return g
        ds = Hdf5Dataset(self, name, dtype, space or _Dataspace(()),
                         layout, filters, attrs)
        self._cache[addr] = ds
        return ds

    def _parse_link(self, body: bytes) -> Tuple[str, int]:
        ver, flags = body[0], body[1]
        off = 2
        if flags & 0x08:
            off += 1  # link type (0 = hard)
        if flags & 0x04:
            off += 8  # creation order
        if flags & 0x10:
            off += 1  # charset
        ln_size = 1 << (flags & 0x3)
        ln = _u(body, off, ln_size)
        off += ln_size
        lname = body[off:off + ln].decode()
        off += ln
        return lname, _u(body, off, 8)

    # ---- old-style groups -------------------------------------------------
    def _read_group_btree(self, btree_addr: int, heap_addr: int
                          ) -> Dict[str, int]:
        d = self.data
        heap_data_addr = _u(d, heap_addr + 24, 8)
        out: Dict[str, int] = {}

        def heap_name(off: int) -> str:
            end = d.index(b"\x00", heap_data_addr + off)
            return d[heap_data_addr + off:end].decode()

        def walk(addr: int):
            if d[addr:addr + 4] == b"SNOD":
                nsyms = _u(d, addr + 6, 2)
                p = addr + 8
                for _ in range(nsyms):
                    name_off = _u(d, p, 8)
                    oh_addr = _u(d, p + 8, 8)
                    out[heap_name(name_off)] = oh_addr
                    p += 40
                return
            if d[addr:addr + 4] != b"TREE":
                raise Hdf5FormatError("expected TREE/SNOD node")
            entries = _u(d, addr + 6, 2)
            p = addr + 8 + 16  # skip left/right siblings
            p += 8  # key0
            for _ in range(entries):
                child = _u(d, p, 8)
                walk(child)
                p += 16  # child + next key

        if btree_addr != UNDEF:
            walk(btree_addr)
        return out

    # ---- chunk b-tree -----------------------------------------------------
    def _iter_chunks(self, btree_addr: int, ndims: int):
        d = self.data
        results = []

        def walk(addr: int):
            if d[addr:addr + 4] != b"TREE":
                raise Hdf5FormatError("expected chunk TREE node")
            level = d[addr + 5]
            entries = _u(d, addr + 6, 2)
            p = addr + 8 + 16
            key_size = 8 + 8 * (ndims + 1)
            for _ in range(entries):
                size = _u(d, p, 4)
                mask = _u(d, p + 4, 4)
                offsets = tuple(_u(d, p + 8 + 8 * i, 8) for i in range(ndims))
                child = _u(d, p + key_size, 8)
                if level == 0:
                    results.append((offsets, size, mask, child))
                else:
                    walk(child)
                p += key_size + 8

        if btree_addr != UNDEF:
            walk(btree_addr)
        return results

    # ---- v4 chunk indexes (HDF5 1.10+ "latest" files) ---------------------
    def _iter_chunks_v4(self, lay: _Layout, shape: Tuple[int, ...],
                        chunk_nbytes: int):
        cdims = lay.chunk_dims
        ndims = len(shape)
        grid = [max(1, -(-shape[i] // cdims[i])) for i in range(ndims)]

        def origin(idx: int) -> Tuple[int, ...]:
            out = []
            for g, c in zip(reversed(grid), reversed(cdims)):
                out.append((idx % g) * c)
                idx //= g
            return tuple(reversed(out))

        if lay.address == UNDEF:
            return []
        if lay.chunk_index == 1:    # single chunk: address is the data
            size = lay.single_size or chunk_nbytes
            return [((0,) * ndims, size, lay.single_mask, lay.address)]
        if lay.chunk_index == 2:    # implicit: dense row-major chunk array
            n = 1
            for g in grid:
                n *= g
            return [(origin(i), chunk_nbytes, 0,
                     lay.address + i * chunk_nbytes) for i in range(n)]
        if lay.chunk_index == 3:    # fixed array
            return self._read_fixed_array(lay.address, origin, chunk_nbytes)
        raise Hdf5FormatError(
            f"chunk index type {lay.chunk_index} unsupported")

    def _read_fixed_array(self, addr: int, origin, chunk_nbytes: int):
        d = self.data
        if d[addr:addr + 4] != b"FAHD":
            raise Hdf5FormatError("bad fixed-array header signature")
        client = d[addr + 5]            # 0 plain, 1 filtered chunks
        entry_size = d[addr + 6]
        page_bits = d[addr + 7]
        nentries = _u(d, addr + 8, 8)
        dblock = _u(d, addr + 16, 8)
        if nentries > (1 << page_bits):
            raise Hdf5FormatError("paged fixed-array chunk index unsupported")
        if dblock == UNDEF:
            return []
        if d[dblock:dblock + 4] != b"FADB":
            raise Hdf5FormatError("bad fixed-array data block signature")
        p = dblock + 6 + 8              # sig+ver+client, header address
        out = []
        for i in range(nentries):
            caddr = _u(d, p, 8)
            if client == 0:
                size, mask = chunk_nbytes, 0
            else:
                sz_len = entry_size - 12
                size = _u(d, p + 8, sz_len)
                mask = _u(d, p + 8 + sz_len, 4)
            if caddr != UNDEF:
                out.append((origin(i), size, mask, caddr))
            p += entry_size
        return out

    # ---- attributes -------------------------------------------------------
    def _parse_attribute(self, body: bytes) -> Tuple[str, Any]:
        ver = body[0]
        if ver == 1:
            name_size = _u(body, 2, 2)
            dt_size = _u(body, 4, 2)
            ds_size = _u(body, 6, 2)
            off = 8
            name = body[off:off + name_size].split(b"\x00")[0].decode()
            off += name_size + (-name_size) % 8
            dt = _parse_datatype(body[off:off + dt_size])
            off += dt_size + (-dt_size) % 8
            space = _parse_dataspace(body[off:off + ds_size])
            off += ds_size + (-ds_size) % 8
        elif ver in (2, 3):
            name_size = _u(body, 2, 2)
            dt_size = _u(body, 4, 2)
            ds_size = _u(body, 6, 2)
            off = 8 + (1 if ver == 3 else 0)
            name = body[off:off + name_size].split(b"\x00")[0].decode()
            off += name_size
            dt = _parse_datatype(body[off:off + dt_size])
            off += dt_size
            space = _parse_dataspace(body[off:off + ds_size])
            off += ds_size
        else:
            raise Hdf5FormatError(f"attribute version {ver}")
        data = body[off:]
        return name, self._attr_value(dt, space, data)

    def _attr_value(self, dt: _Datatype, space: _Dataspace, data: bytes):
        n = space.n_elements
        if dt.cls == 9 and dt.vlen_string:
            vals = [self._read_gheap_object(data, 16 * i) for i in range(n)]
        elif dt.cls == 3:
            vals = [data[i * dt.size:(i + 1) * dt.size].split(b"\x00")[0]
                    .decode("utf-8", "replace") for i in range(n)]
        else:
            npdt = dt.numpy_dtype
            arr = np.frombuffer(data[:n * npdt.itemsize], npdt)
            vals = list(arr)
        if not space.dims:
            return vals[0]
        return np.asarray(vals, dtype=object if dt.cls in (3, 9) else None
                          ).reshape(space.dims)

    # ---- global heap (vlen strings) ---------------------------------------
    def _read_gheap_object(self, ref: bytes, off: int) -> str:
        size = _u(ref, off, 4)
        gaddr = _u(ref, off + 4, 8)
        gidx = _u(ref, off + 12, 4)
        d = self.data
        if d[gaddr:gaddr + 4] != b"GCOL":
            raise Hdf5FormatError("bad global heap signature")
        total = _u(d, gaddr + 8, 8)
        p = gaddr + 16
        end = gaddr + total
        while p < end:
            idx = _u(d, p, 2)
            osize = _u(d, p + 8, 8)
            if idx == 0:
                break
            if idx == gidx:
                return d[p + 16:p + 16 + size].decode("utf-8", "replace")
            p += 16 + osize + (-osize) % 8
        raise Hdf5FormatError(f"global heap object {gidx} not found")
