"""Sparse embedding gradients: densified row exchange for huge tables.

An embedding gradient is a handful of rows of a `[vocab, dim]` table,
yet a dense train step all-reduces (or reduce-scatters) the whole
mostly-zero tensor every step.  This module is the densified
accumulation of assumed-sparse tensors (arXiv:1905.04035, PAPERS.md):
coalesce the rows a batch actually touches and exchange fixed-capacity
index + value blocks instead of the dense table, so per-step comms go
from O(vocab·dim) to O(touched_rows·dim).

The mechanism (wired into ``nn/multilayer._build_train_step`` when an
embedding layer declares ``sparse_grad=True``):

1. **Coalesce outside the gradient** — :func:`coalesce` computes the
   sorted unique touched row ids (``jnp.unique`` with a STATIC
   ``size=capacity``, so shapes stay fixed under jit and every
   ``ShapePolicy`` bucket compiles once) plus the position→slot inverse
   map via ``searchsorted``.
2. **Differentiate row space, not table space** — the step gathers
   ``rows = W[uniq]`` *before* ``value_and_grad`` and substitutes the
   table leaf with the gathered rows (and the ids with their slot map),
   so the table's cotangent is ``[capacity, dim]`` — the dense
   ``[vocab, dim]`` cotangent is never materialized.  The lookup itself
   is :func:`embedding_lookup`, a custom-vjp gather whose backward is
   ONE coalesced ``segment_sum`` (deterministic densified
   accumulation of duplicate ids).
3. **SparseRows carrier** — the coalesced gradient travels as
   :class:`SparseRows` (indices + values, pytree-registered), the
   system's first structurally-sparse gradient leaf.
4. **Lazy row-space updater** — the optax transform runs on
   row-space views (:func:`gather_rows_tree` pulls the touched rows of
   every param-shaped mirror leaf — Adam mu/nu, momentum traces — into
   ``[capacity, dim]`` blocks), and :func:`scatter_rows_tree` writes
   only those rows back.  Untouched rows of the table AND its mirrors
   are bit-identical across the step ("lazy" updater semantics: exact
   for stateless updaters like SGD; stateful updaters skip the decay of
   untouched rows, the standard lazy-Adam trade).

Under a ZeRO-3 mesh (``parallel/sharded.py``) the table and its mirrors
are row-sharded over the data axis, and GSPMD derives the whole
exchange from the argument shardings: the touched-row gather becomes a
shard-local gather + an O(capacity·dim) all-reduce returning rows to
requesters, the backward segment-sum becomes per-shard partials + the
same-sized reduction back to the owner shards, and the scatter-update
stays shard-local — no collective in the partitioned HLO carries
O(vocab·dim) bytes (pinned by the ``train_step[embedding_zero3]``
graftaudit card).

Capacity contract: the per-step exchange block is ``capacity`` rows.
``capacity=None`` derives the exact static bound ``min(n_ids, vocab)``
— overflow is impossible by construction.  An explicit
``sparse_grad_capacity`` below that bound is REFUSED at trace time
(:func:`effective_capacity`): silent gradient truncation is the one
behavior this path must never ship.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SparseRows", "coalesce", "effective_capacity",
           "embedding_lookup", "RowContext", "gather_rows_tree",
           "scatter_rows_tree", "table_is_unambiguous"]


@jax.tree_util.register_pytree_node_class
@dataclass
class SparseRows:
    """Densified-sparse gradient of a ``[n_rows, dim]`` table.

    ``indices``: ``[capacity]`` int32, sorted unique touched row ids;
    unused slots hold ``n_rows`` (one past the last valid row) so a
    ``mode="drop"`` scatter ignores them.  ``values``: ``[capacity,
    dim]`` coalesced per-row gradient values (duplicate ids already
    segment-summed).  ``n_rows`` is static aux data — it defines the
    dense shape without ever allocating it.
    """

    indices: Any
    values: Any
    n_rows: int

    def tree_flatten(self):
        return (self.indices, self.values), (self.n_rows,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    @property
    def capacity(self) -> int:
        return int(self.indices.shape[0])

    @property
    def dim(self) -> int:
        return int(self.values.shape[-1])

    def touched(self):
        """Traced count of real (non-fill) row slots."""
        # explicit accumulator dtype: jnp.sum(int32) widens to i64 under
        # x64, which would put an s64 scalar into the pinned collective
        # census
        return jnp.sum(self.indices < self.n_rows, dtype=jnp.int32)

    def to_dense(self):
        """Materialize the dense ``[n_rows, dim]`` gradient — tests and
        host-side interop ONLY; the train step never calls this (the
        whole point is that the dense tensor does not exist there)."""
        dense = jnp.zeros((self.n_rows, self.dim), self.values.dtype)
        return dense.at[self.indices].add(self.values, mode="drop")  # graftlint: disable=JX027  (documented test/interop escape hatch — the train step itself never densifies)


def effective_capacity(n_ids: int, n_rows: int,
                       configured: Optional[int] = None) -> int:
    """Static row capacity of one step's exchange block.

    The exact bound ``min(n_ids, n_rows)`` can never overflow (a batch
    of ``n_ids`` positions touches at most that many distinct rows).
    ``configured`` may only pad UP to a fixed block size (shape
    stability across ShapePolicy buckets); an undersized capacity is
    refused here, at trace time — the pinned overflow behavior —
    because truncating unique ids would silently drop or misattribute
    gradient mass.
    """
    exact = min(int(n_ids), int(n_rows))
    if configured is None:
        return exact
    configured = int(configured)
    if configured < exact:
        raise ValueError(
            f"sparse_grad_capacity={configured} is below the exact "
            f"touched-row bound min(n_ids={n_ids}, vocab={n_rows}) = "
            f"{exact}: an overflowing capacity would silently truncate "
            "gradient rows — raise the capacity (or leave it None for "
            "the exact bound)")
    return min(configured, int(n_rows))


def coalesce(ids, capacity: int, n_rows: int) -> Tuple[Any, Any]:
    """Coalesce a flat int id vector into ``(uniq, inv)``.

    ``uniq``: ``[capacity]`` sorted unique ids, fill slots = ``n_rows``.
    ``inv``: ``ids``-shaped int32 slot map with ``uniq[inv] == ids`` for
    every position whose id made it into ``uniq`` and ``capacity`` (one
    past the last slot) otherwise — pointing those positions at the
    zero "trash" row of an extended ``[capacity+1, dim]`` row block, so
    their gradient is dropped rather than misattributed.  With
    ``capacity`` from :func:`effective_capacity` every id is always
    found; the guard exists so the contract is positional, not
    assumed.
    """
    capacity = int(capacity)
    flat = ids.reshape(-1).astype(jnp.int32)
    # invalid ids (negative or >= n_rows) collapse onto the fill value
    # FIRST: traced ids bypass the layers' concrete range validation,
    # and an unmasked invalid id would corrupt silently — a negative
    # index wraps in the `.at[...]` scatter (writing the LAST row with
    # a foreign update), and an id > n_rows lands above the fill value,
    # un-sorting `uniq` and breaking the searchsorted slot map.  Masked,
    # an invalid position reads the clamp row forward and sheds its
    # gradient at the dropped fill slot — deterministic, never
    # misattributed.
    flat = jnp.where((flat >= 0) & (flat < n_rows), flat,
                     jnp.int32(n_rows))
    # hand-rolled unique (sort + first-occurrence scatter) instead of
    # jnp.unique: every intermediate stays int32 regardless of
    # jax_enable_x64, so the compiled collective census — which the
    # committed graftaudit card pins — is identical across x64 modes
    # (jnp.unique's internal iota is i64 under x64)
    s = jnp.sort(flat)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]])
    pos = jnp.cumsum(first.astype(jnp.int32)) - 1     # slot per element
    write = jnp.where(first, pos, jnp.int32(capacity))
    uniq = jnp.full((capacity,), jnp.int32(n_rows), jnp.int32) \
        .at[write].set(s, mode="drop")
    slot = jnp.searchsorted(uniq, flat).astype(jnp.int32)
    slot_c = jnp.clip(slot, 0, capacity - 1)
    inv = jnp.where(uniq[slot_c] == flat, slot_c,
                    jnp.int32(capacity))
    return uniq, inv.reshape(ids.shape)


# ---------------------------------------------------------------- lookup
@jax.custom_vjp
def embedding_lookup(table, idx):
    """Gather ``table[idx]`` whose backward is a single coalesced
    ``segment_sum`` — the densified accumulation of arXiv:1905.04035.

    In the sparse train step ``table`` is the substituted
    ``[capacity+1, dim]`` touched-row block, so the cotangent this
    produces IS the :class:`SparseRows` value block (plus the trash
    row); the dense ``[vocab, dim]`` cotangent never exists.  With a
    full table it degrades to the ordinary gather/scatter-add pair.
    Id hygiene lives upstream: `EmbeddingLayer` raises
    ``InvalidInputError`` on concrete out-of-range ids, and the train
    step's :func:`coalesce` masks traced invalid ids onto the dropped
    fill slot (clamp-row forward, no gradient — never a wrapped or
    misattributed row write).
    """
    return table[idx]


def _lookup_fwd(table, idx):
    return table[idx], (idx, table.shape[0])


def _lookup_bwd(res, ct):
    idx, n_rows = res
    dim = ct.shape[-1]
    grad = jax.ops.segment_sum(ct.reshape(-1, dim),
                               idx.reshape(-1).astype(jnp.int32),
                               num_segments=n_rows)
    # integer primal: float0 cotangent (JAX's "no tangent" dtype)
    return grad.astype(ct.dtype), np.zeros(idx.shape, jax.dtypes.float0)


embedding_lookup.defvjp(_lookup_fwd, _lookup_bwd)


# ------------------------------------------------------------ row context
def table_is_unambiguous(params, table_shape) -> bool:
    """The row-space mirror walk identifies the table's optimizer
    mirrors by shape (optax state trees don't carry param paths through
    ``multi_transform`` masking).  That is only sound when exactly ONE
    param leaf has the table's shape — a twin same-shaped parameter
    would alias its mirrors into the row swap."""
    n = sum(1 for leaf in jax.tree_util.tree_leaves(params)
            if getattr(leaf, "shape", None) == tuple(table_shape))
    return n == 1


class RowContext:
    """One step's touched-row workspace: built at trace time from the
    batch ids, consumed by the substitution / update / scatter stages
    of the sparse train step.  Plain object (not a pytree) — it lives
    inside a single trace."""

    __slots__ = ("uniq", "inv", "capacity", "n_rows", "rows", "rows_ext",
                 "x_sub")

    def __init__(self, W, ids, configured_capacity: Optional[int]):
        n_rows, dim = int(W.shape[0]), int(W.shape[1])
        n_ids = int(np.prod(ids.shape, dtype=np.int64))
        cap = effective_capacity(n_ids, n_rows, configured_capacity)
        uniq, inv = coalesce(ids, cap, n_rows)
        self.uniq, self.inv = uniq, inv
        self.capacity, self.n_rows = cap, n_rows
        # fill slots (uniq == n_rows) clamp-gather the last real row;
        # their zero-grad "updates" are dropped at scatter time
        self.rows = W[jnp.clip(uniq, 0, n_rows - 1)]
        # +1 zero trash row: positions whose id missed the block (never,
        # under effective_capacity) read zeros and shed their gradient
        self.rows_ext = jnp.concatenate(
            [self.rows, jnp.zeros((1, dim), W.dtype)], axis=0)
        self.x_sub = inv

    def touched(self):
        """Traced count of real (non-fill) row slots this step touches
        (fixed-i32 accumulator — see :meth:`SparseRows.touched`)."""
        return jnp.sum(self.uniq < self.n_rows, dtype=jnp.int32)

    def scatter_rows(self, table, new_rows):
        """Write the updated touched rows back into the full table;
        fill-slot indices (== n_rows) drop."""
        return table.at[self.uniq].set(new_rows, mode="drop")

    def wrap_grad(self, g_rows_ext) -> SparseRows:
        """[capacity+1, dim] cotangent (from the substituted lookup's
        backward) → the SparseRows carrier; the trash row is dropped
        (zero under the capacity contract)."""
        return SparseRows(self.uniq, g_rows_ext[:self.capacity],
                          self.n_rows)


def gather_rows_tree(tree, ctx: RowContext):
    """Row-space view of an optimizer-state pytree: every leaf shaped
    exactly like the table (its mu/nu/trace mirrors) is gathered down
    to the ``[capacity, dim]`` touched-row block; every other leaf
    (counts, scalars, other params' mirrors) passes through untouched.
    Shape-keyed on purpose — see :func:`table_is_unambiguous`."""
    table_shape = (ctx.n_rows, int(ctx.rows.shape[1]))
    safe = jnp.clip(ctx.uniq, 0, ctx.n_rows - 1)

    def pick(leaf):
        if getattr(leaf, "shape", None) == table_shape and \
                hasattr(leaf, "dtype") and \
                jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf[safe]
        return leaf

    return jax.tree_util.tree_map(pick, tree)


def scatter_rows_tree(old_tree, new_row_tree, ctx: RowContext):
    """Inverse of :func:`gather_rows_tree` after the row-space update:
    mirror leaves get their touched rows scattered back (untouched rows
    keep their pre-step bytes — the lazy semantics); everything else
    takes the updated value."""
    table_shape = (ctx.n_rows, int(ctx.rows.shape[1]))
    row_shape = (ctx.capacity, int(ctx.rows.shape[1]))

    def put(old, new):
        # the SAME classification gather_rows_tree used (shape AND
        # inexact dtype): with capacity == vocab the two shapes
        # coincide, and a table-shaped integer state leaf the gather
        # passed through must not be row-permuted here
        if getattr(old, "shape", None) == table_shape and \
                getattr(new, "shape", None) == row_shape and \
                hasattr(old, "dtype") and \
                jnp.issubdtype(old.dtype, jnp.inexact):
            return old.at[ctx.uniq].set(new, mode="drop")
        return new

    return jax.tree_util.tree_map(put, old_tree, new_row_tree)
