"""Lightweight classification result DTOs.

Reference ``nn/simple/binary/BinaryClassificationResult.java`` and
``nn/simple/multiclass/RankClassificationResult.java`` — small
serialization-friendly holders returned by simple classifier facades.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["BinaryClassificationResult", "RankClassificationResult"]


@dataclass
class BinaryClassificationResult:
    """One binary decision: probability + thresholded label (reference
    BinaryClassificationResult)."""
    probability: float
    threshold: float = 0.5

    @property
    def value(self) -> bool:
        return self.probability >= self.threshold

    def to_dict(self) -> dict:
        return {"probability": self.probability,
                "threshold": self.threshold, "value": self.value}


class RankClassificationResult:
    """Class ranking for a batch of probability rows (reference
    RankClassificationResult: ranked labels + max-index helpers)."""

    def __init__(self, probabilities, labels: Optional[Sequence[str]] = None):
        self.probabilities = np.asarray(probabilities, np.float64)
        if self.probabilities.ndim == 1:
            self.probabilities = self.probabilities[None]
        n = self.probabilities.shape[1]
        self.labels = list(labels) if labels is not None else \
            [str(i) for i in range(n)]
        if len(self.labels) != n:
            raise ValueError(f"{len(self.labels)} labels for {n} classes")

    def max_index(self, row: int = 0) -> int:
        return int(np.argmax(self.probabilities[row]))

    def max_label(self, row: int = 0) -> str:
        return self.labels[self.max_index(row)]

    def rank(self, row: int = 0) -> List[str]:
        """Labels sorted most→least probable for one example."""
        order = np.argsort(-self.probabilities[row], kind="stable")
        return [self.labels[i] for i in order]

    def probability(self, row: int, label: str) -> float:
        return float(self.probabilities[row][self.labels.index(label)])

    def to_dict(self) -> dict:
        return {"labels": self.labels,
                "probabilities": self.probabilities.tolist()}
