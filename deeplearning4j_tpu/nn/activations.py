"""Activation functions.

TPU-native analogue of the reference's activation registry (DL4J exposes an
``Activation`` enum resolved to ``IActivation`` math objects; see
``deeplearning4j-nn/.../nn/conf/layers/BaseLayer`` usage and the nd4j activation
classes referenced by ``nn/conf/NeuralNetConfiguration.java``).  Here each
activation is a pure JAX function usable inside ``jax.jit`` — XLA fuses these
into the surrounding matmul/conv, which is the TPU replacement for libnd4j's
hand-written elementwise kernels.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array

_REGISTRY: Dict[str, Callable[[Array], Array]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn
    return deco


# parameterized activations: "name:param" (e.g. "leakyrelu:0.3") — a plain
# string so layer configs stay JSON/YAML-serializable (the reference carries
# the parameter on the IActivation object, e.g. ActivationLReLU(alpha))
_PARAMETERIZED: Dict[str, Callable[[float], Callable[[Array], Array]]] = {}


def register_parameterized(name: str):
    def deco(factory):
        _PARAMETERIZED[name.lower()] = factory
        return factory
    return deco


def get(name) -> Callable[[Array], Array]:
    """Resolve an activation by name (case-insensitive). Callables pass
    through.  ``"name:param"`` resolves a parameterized activation, e.g.
    ``"leakyrelu:0.3"``."""
    if callable(name):
        return name
    s = name.lower()
    if ":" in s:
        base, _, arg = s.partition(":")
        if base in _PARAMETERIZED:
            try:
                param = float(arg)
            except ValueError:
                raise ValueError(
                    f"Bad parameter '{arg}' for activation '{base}': expected "
                    f"a number (e.g. '{base}:0.3'). "
                    f"Parameterized activations: {sorted(_PARAMETERIZED)}") from None
            return _PARAMETERIZED[base](param)
        raise ValueError(
            f"Unknown parameterized activation '{base}'. "
            f"Available: {sorted(_PARAMETERIZED)}")
    try:
        return _REGISTRY[s]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(_REGISTRY)}") from None


def names():
    return sorted(_REGISTRY)


@register("identity")
@register("linear")
def identity(x):
    return x


@register("relu")
def relu(x):
    # NOTE (round-2 negative result): an output-keyed custom-VJP relu
    # (bwd mask from y>0, letting the saved residual alias the next
    # layer's input) changed NOTHING — XLA's bytes-accessed was identical
    # (81.886 GB for the ResNet50 step), i.e. the compiler already dedupes
    # the relu residual against the saved output; and custom_vjp would
    # break forward-mode jvp.  Keep the plain primitive.
    return jax.nn.relu(x)


@register("relu6")
def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


@register("leakyrelu")
def leakyrelu(x):
    return jax.nn.leaky_relu(x, negative_slope=0.01)


@register("elu")
def elu(x):
    return jax.nn.elu(x)


@register("selu")
def selu(x):
    return jax.nn.selu(x)


@register("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register("tanh")
def tanh(x):
    return jnp.tanh(x)


@register("rationaltanh")
def rationaltanh(x):
    # tanh approximation: 1.7159 * tanh(2x/3), clipped to [-1,1] range behaviour
    a = 1.7159 * jnp.tanh(2.0 * x / 3.0)
    return jnp.clip(a, -1.0, 1.0)


@register("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("hardsigmoid")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register("logsoftmax")
def logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


@register("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register("cube")
def cube(x):
    return x ** 3


@register("swish")
@register("silu")
def swish(x):
    return jax.nn.silu(x)


@register("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register("rrelu")
def rrelu(x):
    # deterministic midpoint variant (train-time randomized slope averaged)
    return jnp.where(x >= 0, x, x * (1.0 / 8.0 + 1.0 / 3.0) / 2.0)


@register("thresholdedrelu")
def thresholdedrelu(x):
    return jnp.where(x > 1.0, x, 0.0)


@register_parameterized("leakyrelu")
@register_parameterized("lrelu")
def _leakyrelu_p(alpha: float):
    return lambda x: jax.nn.leaky_relu(x, negative_slope=alpha)


@register_parameterized("elu")
def _elu_p(alpha: float):
    return lambda x: jax.nn.elu(x, alpha=alpha)


@register_parameterized("thresholdedrelu")
def _thresholdedrelu_p(theta: float):
    return lambda x: jnp.where(x > theta, x, 0.0)
