"""Reconstruction distributions for the variational autoencoder.

Reference ``nn/conf/layers/variational/``: ``ReconstructionDistribution``
implementations (Bernoulli, Gaussian, Exponential, Composite,
LossFunctionWrapper).  Each maps a slice of the decoder pre-output to
p(x|z): ``dist_params_size`` says how many pre-output units a data dimension
needs, ``neg_log_prob`` scores data under the distribution, ``sample``/
``mean`` generate (reference ``generateAtMeanGivenZ``/``generateRandomGivenZ``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .. import activations as _act
from .. import losses as _losses

Array = jax.Array
_EPS = 1e-7


@dataclass
class ReconstructionDistribution:
    def dist_params_size(self, data_size: int) -> int:
        return data_size

    def neg_log_prob(self, x: Array, preout: Array, average: bool = True) -> Array:
        raise NotImplementedError

    def mean(self, preout: Array) -> Array:
        raise NotImplementedError

    def sample(self, key, preout: Array) -> Array:
        raise NotImplementedError

    def has_loss_function(self) -> bool:
        return False


@register_serde
@dataclass
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = Bernoulli(sigmoid(preout)) (reference
    ``BernoulliReconstructionDistribution.java``)."""
    activation: str = "sigmoid"

    def neg_log_prob(self, x, preout, average=True):
        p = _act.get(self.activation)(preout)
        p = jnp.clip(p, _EPS, 1 - _EPS)
        ll = x * jnp.log(p) + (1 - x) * jnp.log(1 - p)
        per_ex = -jnp.sum(ll, axis=-1)
        return jnp.mean(per_ex) if average else jnp.sum(per_ex)

    def mean(self, preout):
        return _act.get(self.activation)(preout)

    def sample(self, key, preout):
        return jax.random.bernoulli(
            key, self.mean(preout)).astype(preout.dtype)


@register_serde
@dataclass
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = N(mu, sigma^2); preout packs [mu, log sigma^2] (reference
    ``GaussianReconstructionDistribution.java`` — 2 params per dimension)."""
    activation: str = "identity"

    def dist_params_size(self, data_size: int) -> int:
        return 2 * data_size

    def _split(self, preout):
        n = preout.shape[-1] // 2
        mu = _act.get(self.activation)(preout[..., :n])
        log_var = preout[..., n:]
        return mu, log_var

    def neg_log_prob(self, x, preout, average=True):
        mu, log_var = self._split(preout)
        log_var = jnp.clip(log_var, -20.0, 20.0)
        var = jnp.exp(log_var)
        ll = -0.5 * (jnp.log(2 * jnp.pi) + log_var + (x - mu) ** 2 / var)
        per_ex = -jnp.sum(ll, axis=-1)
        return jnp.mean(per_ex) if average else jnp.sum(per_ex)

    def mean(self, preout):
        return self._split(preout)[0]

    def sample(self, key, preout):
        mu, log_var = self._split(preout)
        std = jnp.exp(0.5 * jnp.clip(log_var, -20.0, 20.0))
        return mu + std * jax.random.normal(key, mu.shape, mu.dtype)


@register_serde
@dataclass
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """p(x|z) = Exp(lambda = exp(preout)) — reference
    ``ExponentialReconstructionDistribution.java`` parameterizes via
    gamma = log(lambda)."""
    activation: str = "identity"

    def neg_log_prob(self, x, preout, average=True):
        gamma = _act.get(self.activation)(preout)
        gamma = jnp.clip(gamma, -20.0, 20.0)
        lam = jnp.exp(gamma)
        ll = gamma - lam * x
        per_ex = -jnp.sum(ll, axis=-1)
        return jnp.mean(per_ex) if average else jnp.sum(per_ex)

    def mean(self, preout):
        return jnp.exp(-jnp.clip(_act.get(self.activation)(preout), -20.0, 20.0))

    def sample(self, key, preout):
        u = jax.random.uniform(key, preout.shape, preout.dtype, _EPS, 1.0)
        return -jnp.log(u) * self.mean(preout)


@register_serde
@dataclass
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over slices of the data vector (reference
    ``CompositeReconstructionDistribution.java``).  ``components`` is a list
    of (data_size, distribution)."""
    components: List[Any] = field(default_factory=list)

    def add(self, data_size: int, dist) -> "CompositeReconstructionDistribution":
        self.components.append([int(data_size), dist])
        return self

    def dist_params_size(self, data_size: int) -> int:
        total_data = sum(c[0] for c in self.components)
        if data_size != total_data:
            raise ValueError(
                f"composite covers {total_data} dims, data has {data_size}")
        return sum(c[1].dist_params_size(c[0]) for c in self.components)

    def _slices(self):
        xi = pi = 0
        for size, dist in self.components:
            psize = dist.dist_params_size(size)
            yield (xi, xi + size), (pi, pi + psize), dist
            xi += size
            pi += psize

    def neg_log_prob(self, x, preout, average=True):
        # follow the data dtype: a dtype-defaulted zeros(()) is f64 under
        # x64 and would promote the whole pretrain loss (graftaudit AX001)
        total = jnp.zeros((), dtype=preout.dtype)
        for (x0, x1), (p0, p1), dist in self._slices():
            total = total + dist.neg_log_prob(x[..., x0:x1],
                                              preout[..., p0:p1], average)
        return total

    def mean(self, preout):
        return jnp.concatenate([d.mean(preout[..., p0:p1])
                                for (_, _), (p0, p1), d in self._slices()],
                               axis=-1)

    def sample(self, key, preout):
        outs = []
        for i, ((_, _), (p0, p1), d) in enumerate(self._slices()):
            outs.append(d.sample(jax.random.fold_in(key, i),
                                 preout[..., p0:p1]))
        return jnp.concatenate(outs, axis=-1)


@register_serde
@dataclass
class LossFunctionWrapper(ReconstructionDistribution):
    """Plain loss as a pseudo-distribution (reference
    ``LossFunctionWrapper.java`` — turns the VAE into a standard deep AE)."""
    loss: str = "mse"
    activation: str = "identity"

    def has_loss_function(self) -> bool:
        return True

    def neg_log_prob(self, x, preout, average=True):
        val = _losses.get(self.loss)(x, preout, self.activation, None)
        if not average:
            val = val * x.shape[0]
        return val

    def mean(self, preout):
        return _act.get(self.activation)(preout)

    def sample(self, key, preout):
        return self.mean(preout)
