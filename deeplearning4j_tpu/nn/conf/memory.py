"""Pre-training memory estimation (reference ``nn/conf/memory/``:
``MemoryReport.java``, ``LayerMemoryReport.java``, ``NetworkMemoryReport.java``,
``MemoryUseMode.java``).

Two tiers, both first-class on TPU where "does this batch fit HBM?" is a
pre-flight question:

1. **Analytic report** (`memory_report` / `memory_report_graph`): no
   compile needed.  Exact for parameters / gradients / updater state /
   mixed-precision parameter copies (validated within 1% of XLA's argument
   accounting on ResNet50); an UPPER BOUND for training activations on
   TPU — XLA's fusion + scheduling keeps only a fraction of vertex
   outputs live (measured ~0.53x for ResNet50-bf16, ~0.1x for LeNet
   where cheap convs are recomputed).  Backend conv scratch (e.g. the CPU
   backend's im2col
   buffers) is NOT modeled — on CPU small conv nets can exceed the
   activation bound; use the exact tier there.
2. **Exact report** (`xla_memory_report`): lower + compile the real train
   step and return XLA's own buffer-assignment numbers
   (argument/output/temp/alias bytes).  XLA *is* the allocator on TPU, so
   this is the ground truth the reference's NetworkMemoryReport
   approximates by hand — at the cost of one compile.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .input_type import InputType

__all__ = ["LayerMemoryReport", "NetworkMemoryReport", "MemoryUseMode",
           "memory_report", "memory_report_graph", "xla_memory_report"]


class MemoryUseMode:
    INFERENCE = "INFERENCE"
    TRAINING = "TRAINING"


def _elems(itype: InputType) -> int:
    return int(np.prod([d for d in itype.shape(1)[1:]]))


@dataclass
class LayerMemoryReport:
    """Per-layer estimate, in ELEMENTS (multiply by dtype width for bytes)."""
    layer_name: str
    layer_type: str
    n_params: int
    activation_elems_per_example: int
    # updater state multiplier: sgd=0, momentum/rmsprop=1, adam=2 slots/param
    updater_state_elems: int = 0


_UPDATER_SLOTS = {"Sgd": 0, "Nesterovs": 1, "Adam": 2, "AdamW": 2,
                  "AdaMax": 2, "AdaGrad": 1, "AdaDelta": 2, "RmsProp": 1,
                  "Nadam": 2, "AmsGrad": 3}


@dataclass
class NetworkMemoryReport:
    """Whole-network roll-up (reference ``NetworkMemoryReport.java``).

    Byte accounting (training):
      params (f32 masters) + gradients (f32) + updater state
      + bf16 parameter copy when ``compute_dtype`` is low-precision
      + batch x layer-boundary activations in the compute dtype (an upper
        bound on TPU; remat recomputes only interior intermediates this
        term never counted, so it does not change the bound).
    """
    layer_reports: List[LayerMemoryReport]
    model_class: str
    param_bytes: int = 4            # master params / grads / updater state
    activation_bytes: int = 4       # compute dtype width
    mixed_precision: bool = False   # separate low-precision param copy
    remat: bool = False             # cache_mode("remat")

    @property
    def total_params(self) -> int:
        return sum(r.n_params for r in self.layer_reports)

    @property
    def total_updater_elems(self) -> int:
        return sum(r.updater_state_elems for r in self.layer_reports)

    @property
    def activation_elems_per_example(self) -> int:
        return sum(r.activation_elems_per_example for r in self.layer_reports)

    def total_memory_bytes(self, batch: int,
                           mode: str = MemoryUseMode.TRAINING) -> int:
        p = self.total_params
        if mode == MemoryUseMode.TRAINING:
            b = p * self.param_bytes * 2                   # params + grads
            b += self.total_updater_elems * self.param_bytes
            if self.mixed_precision:
                b += p * self.activation_bytes             # bf16 copy
            # layer-boundary activations: per-layer jax.checkpoint (remat)
            # saves exactly these and recomputes only interior
            # intermediates, which this term never counted — so the bound
            # is unchanged by remat (just tighter in practice)
            acts = self.activation_elems_per_example * batch
            b += acts * self.activation_bytes
            return b
        # inference: params + the two widest consecutive activations (XLA
        # reuses earlier buffers once consumed).  The inference path does
        # NOT cast to the compute dtype (only the train step does), so
        # everything is priced at the full parameter width.
        acts = [r.activation_elems_per_example for r in self.layer_reports]
        peak_acts = max((acts[i] + acts[i + 1]
                         for i in range(len(acts) - 1)),
                        default=acts[0] if acts else 0)
        return (p + peak_acts * batch) * self.param_bytes

    def to_string(self, batch: int = 32) -> str:
        lines = [f"Network memory report ({self.model_class}), "
                 f"batch={batch}, params {self.param_bytes}B, "
                 f"activations {self.activation_bytes}B"
                 + (", remat" if self.remat else ""),
                 f"{'layer':<24}{'type':<24}{'params':>12}{'act/ex':>12}"]
        for r in self.layer_reports:
            lines.append(f"{r.layer_name:<24}{r.layer_type:<24}"
                         f"{r.n_params:>12}{r.activation_elems_per_example:>12}")
        lines.append(f"total params: {self.total_params} "
                     f"(+{self.total_updater_elems} updater elems)")
        for mode in (MemoryUseMode.INFERENCE, MemoryUseMode.TRAINING):
            mb = self.total_memory_bytes(batch, mode) / 2**20
            bound = " (upper bound)" if mode == MemoryUseMode.TRAINING else ""
            lines.append(f"estimated {mode.lower()} memory: "
                         f"{mb:.1f} MiB{bound}")
        return "\n".join(lines)


def _updater_slots(conf) -> int:
    upd = conf.defaults.get("updater")
    name = type(upd).__name__ if upd is not None else "Sgd"
    return _UPDATER_SLOTS.get(name, 1)


def _dtype_fields(conf) -> Dict:
    cdtype = conf.defaults.get("compute_dtype")
    low = cdtype in ("bfloat16", "float16")
    return {"param_bytes": 4,
            "activation_bytes": 2 if low else 4,
            "mixed_precision": low,
            "remat": conf.defaults.get("cache_mode") == "remat"}


def memory_report(conf, model_class: str = "MultiLayerNetwork"
                  ) -> NetworkMemoryReport:
    """Build a report from a built MultiLayerConfiguration (needs
    ``layer_input_types`` resolved — i.e. after ``.build()``)."""
    if (not conf.layer_input_types
            or any(t is None for t in conf.layer_input_types)):
        raise ValueError("configuration has no resolved input types; "
                         "build it with .set_input_type(...)")
    slots = _updater_slots(conf)
    reports = []
    for i, layer in enumerate(conf.layers):
        itype = conf.layer_input_types[i]
        otype = layer.output_type(itype)
        n_params = layer.n_params(itype) if layer.has_params() else 0
        reports.append(LayerMemoryReport(
            layer_name=layer.name or f"layer_{i}",
            layer_type=type(layer).__name__,
            n_params=n_params,
            activation_elems_per_example=_elems(otype),
            updater_state_elems=n_params * slots))
    return NetworkMemoryReport(reports, model_class, **_dtype_fields(conf))


def memory_report_graph(conf, model_class: str = "ComputationGraph"
                        ) -> NetworkMemoryReport:
    """Report for a built ComputationGraphConfiguration: every vertex's
    output counts toward the activation term (resolve() must have run)."""
    if not conf.vertex_input_types:
        raise ValueError("graph configuration is not resolved; build it "
                         "with input types set")
    slots = _updater_slots(conf)
    reports = []
    for name, node in conf.vertices.items():
        ot = conf.vertex_output_type(name)
        if ot is None:
            continue
        layer = getattr(node, "layer", None)
        n_params = 0
        if layer is not None and layer.has_params():
            itypes = conf.vertex_input_types.get(name) or []
            if itypes:
                it = itypes[0]
                pre = getattr(node, "preprocessor", None)
                if pre is not None:
                    it = pre.output_type(it)
                n_params = layer.n_params(it)
        reports.append(LayerMemoryReport(
            layer_name=name,
            layer_type=type(layer or node).__name__,
            n_params=n_params,
            activation_elems_per_example=_elems(ot),
            updater_state_elems=n_params * slots))
    return NetworkMemoryReport(reports, model_class, **_dtype_fields(conf))


def xla_memory_report(model, features, labels) -> Dict[str, int]:
    """EXACT memory accounting (or None when the backend exposes no
    buffer-assignment analysis): lower + compile the model's real train step
    and return XLA's buffer-assignment numbers.  On TPU, XLA is the
    allocator, so this is ground truth (one compile of cost; the compile is
    cached, so a subsequent ``fit`` on the same shapes reuses it).

    Returns {argument_bytes, output_bytes, temp_bytes, alias_bytes,
    total_bytes} — ``total = argument + output + temp - alias`` (donated
    params/updater buffers alias their outputs).
    """
    import jax
    import jax.numpy as jnp

    from ..computation_graph import ComputationGraph

    if model.params == {}:
        model.init()
    is_graph = isinstance(model, ComputationGraph)
    step = model._get_jitted("train_step")
    model._rng, key = jax.random.split(model._rng)
    x = [jnp.asarray(a) for a in features] if is_graph \
        else jnp.asarray(features)
    y = [jnp.asarray(a) for a in labels] if is_graph else jnp.asarray(labels)
    args = (model.params, model.state, model.opt_state, key, x, y,
            None, None)
    try:
        ma = step.lower(*args).compile().memory_analysis()
    except NotImplementedError:
        ma = None
    if ma is None:   # backend doesn't expose buffer assignment
        return None
    out = {"argument_bytes": int(ma.argument_size_in_bytes),
           "output_bytes": int(ma.output_size_in_bytes),
           "temp_bytes": int(ma.temp_size_in_bytes),
           "alias_bytes": int(ma.alias_size_in_bytes)}
    out["total_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                          + out["temp_bytes"] - out["alias_bytes"])
    return out
