"""Pre-training memory estimation (reference ``nn/conf/memory/``:
``MemoryReport.java``, ``LayerMemoryReport.java``, ``NetworkMemoryReport.java``,
``MemoryUseMode.java``).

TPU framing: under jit there are no per-layer workspaces to model — the
estimate covers the XLA-visible components: parameters, optimizer (updater)
state, gradients (training), and per-layer activations, with the inference
path assuming XLA's buffer reuse keeps only the widest two consecutive
activations live.  Re-materialisation (``jax.checkpoint``) would shrink the
training-activation term; the report states the un-remat ceiling.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .input_type import InputType

__all__ = ["LayerMemoryReport", "NetworkMemoryReport", "MemoryUseMode"]


class MemoryUseMode:
    INFERENCE = "INFERENCE"
    TRAINING = "TRAINING"


def _elems(itype: InputType) -> int:
    return int(np.prod([d for d in itype.shape(1)[1:]]))


@dataclass
class LayerMemoryReport:
    """Per-layer estimate, in ELEMENTS (multiply by dtype width for bytes)."""
    layer_name: str
    layer_type: str
    n_params: int
    activation_elems_per_example: int
    # updater state multiplier: sgd=0, momentum/rmsprop=1, adam=2 slots/param
    updater_state_elems: int = 0

    def total_training_elems(self, batch: int) -> int:
        # params + grads + updater state + activations
        return (self.n_params * 2 + self.updater_state_elems
                + self.activation_elems_per_example * batch)

    def total_inference_elems(self, batch: int) -> int:
        return self.n_params + self.activation_elems_per_example * batch


_UPDATER_SLOTS = {"Sgd": 0, "Nesterovs": 1, "Adam": 2, "AdamW": 2,
                  "AdaMax": 2, "AdaGrad": 1, "AdaDelta": 2, "RmsProp": 1,
                  "Nadam": 2, "AmsGrad": 3}


@dataclass
class NetworkMemoryReport:
    """Whole-network roll-up (reference ``NetworkMemoryReport.java``)."""
    layer_reports: List[LayerMemoryReport]
    model_class: str
    bytes_per_element: int = 4

    @property
    def total_params(self) -> int:
        return sum(r.n_params for r in self.layer_reports)

    def total_memory_bytes(self, batch: int,
                           mode: str = MemoryUseMode.TRAINING) -> int:
        if mode == MemoryUseMode.TRAINING:
            elems = sum(r.total_training_elems(batch)
                        for r in self.layer_reports)
        else:
            # params everywhere + the two widest consecutive activations
            # (XLA reuses earlier buffers once consumed)
            acts = [r.activation_elems_per_example for r in self.layer_reports]
            peak_acts = max((acts[i] + acts[i + 1]
                             for i in range(len(acts) - 1)),
                            default=acts[0] if acts else 0)
            elems = self.total_params + peak_acts * batch
        return elems * self.bytes_per_element

    def to_string(self, batch: int = 32) -> str:
        lines = [f"Network memory report ({self.model_class}), "
                 f"batch={batch}, {self.bytes_per_element}B/elem",
                 f"{'layer':<24}{'type':<24}{'params':>12}{'act/ex':>12}"]
        for r in self.layer_reports:
            lines.append(f"{r.layer_name:<24}{r.layer_type:<24}"
                         f"{r.n_params:>12}{r.activation_elems_per_example:>12}")
        lines.append(f"total params: {self.total_params}")
        for mode in (MemoryUseMode.INFERENCE, MemoryUseMode.TRAINING):
            mb = self.total_memory_bytes(batch, mode) / 2**20
            lines.append(f"estimated {mode.lower()} memory: {mb:.1f} MiB")
        return "\n".join(lines)


def _updater_slots(conf) -> int:
    upd = conf.defaults.get("updater")
    name = type(upd).__name__ if upd is not None else "Sgd"
    return _UPDATER_SLOTS.get(name, 1)


def memory_report(conf, model_class: str = "MultiLayerNetwork"
                  ) -> NetworkMemoryReport:
    """Build a report from a built MultiLayerConfiguration (needs
    ``layer_input_types`` resolved — i.e. after ``.build()``)."""
    if (not conf.layer_input_types
            or any(t is None for t in conf.layer_input_types)):
        raise ValueError("configuration has no resolved input types; "
                         "build it with .set_input_type(...)")
    slots = _updater_slots(conf)
    reports = []
    for i, layer in enumerate(conf.layers):
        itype = conf.layer_input_types[i]
        otype = layer.output_type(itype)
        n_params = layer.n_params(itype) if layer.has_params() else 0
        reports.append(LayerMemoryReport(
            layer_name=layer.name or f"layer_{i}",
            layer_type=type(layer).__name__,
            n_params=n_params,
            activation_elems_per_example=_elems(otype),
            updater_state_elems=n_params * slots))
    return NetworkMemoryReport(reports, model_class)
