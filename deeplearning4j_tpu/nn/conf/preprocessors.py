"""Input preprocessors — reshape adapters between layer families.

Analogue of ``nn/conf/preprocessor/`` (CnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, RnnToCnnPreProcessor, …).  In the reference these
implement explicit backprop; here they are pure reshapes/transposes that JAX
differentiates through automatically (and XLA folds into layout assignment —
free on TPU).

Layout notes: images are NHWC (TPU-native; the reference is NCHW) and time
series are [batch, time, features] (the reference is [batch, features, time]).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from ...utils.serde import register_serde
from .input_type import InputType


@dataclass
class InputPreProcessor:
    def pre_process(self, x, mask=None):
        raise NotImplementedError

    def output_type(self, itype: InputType) -> InputType:
        raise NotImplementedError

    def feed_forward_mask(self, mask, itype: InputType):
        return mask


@register_serde
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return x.reshape(x.shape[0], -1)

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(itype.height * itype.width * itype.channels)


@register_serde
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, itype: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_serde
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[b*t, f] -> [b, t, f] is not statically known; reference instead maps
    [b, f] -> [b, 1, f] when used directly, and inside MLN handles the 2d<->3d
    flattening around dense layers in RNN nets. We implement the reference's
    actual contract: reshape flattened time-distributed activations back to 3d.
    """
    timesteps: int = -1

    def pre_process(self, x, mask=None):
        if self.timesteps > 0:
            return x.reshape(-1, self.timesteps, x.shape[-1])
        return x[:, None, :]

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(itype.size, self.timesteps)


@register_serde
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] (time-distributed dense, reference semantics)."""

    def pre_process(self, x, mask=None):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(itype.size)

    def feed_forward_mask(self, mask, itype):
        if mask is None:
            return None
        return mask.reshape(-1)


@register_serde
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0
    timesteps: int = -1

    def pre_process(self, x, mask=None):
        flat = x.reshape(x.shape[0], -1)
        if self.timesteps > 0:
            return flat.reshape(-1, self.timesteps, flat.shape[-1] )
        return flat[:, None, :]

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(itype.height * itype.width * itype.channels,
                                   self.timesteps)


@register_serde
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, itype: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_serde
@dataclass
class CnnFlatToCnnPreProcessor(InputPreProcessor):
    """Flattened image rows -> NHWC (reference: input type CNNFlat handling)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def pre_process(self, x, mask=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, itype: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)
