"""Parameter constraints, applied after each update step.

Analogue of ``nn/conf/constraint/``: MaxNormConstraint, MinMaxNormConstraint,
NonNegativeConstraint, UnitNormConstraint.  Applied inside the jitted train
step right after the optimizer update (reference applies them in
``StochasticGradientDescent.optimize()`` :98 via ``applyConstraints``).

Norms are computed over all axes except the output-unit axis (last), matching
the reference's per-output-neuron norm semantics for dense/conv kernels.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ...utils.serde import register_serde

_EPS = 1e-8


def _unit_norms(w):
    if w.ndim <= 1:
        return jnp.abs(w)
    axes = tuple(range(w.ndim - 1))
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


@dataclass
class LayerConstraint:
    apply_to_weights: bool = True
    apply_to_biases: bool = False

    def apply(self, param):  # pragma: no cover - abstract
        raise NotImplementedError


@register_serde
@dataclass
class MaxNormConstraint(LayerConstraint):
    max_norm: float = 2.0

    def apply(self, param):
        n = _unit_norms(param)
        scale = jnp.minimum(1.0, self.max_norm / (n + _EPS))
        return param * scale


@register_serde
@dataclass
class MinMaxNormConstraint(LayerConstraint):
    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def apply(self, param):
        n = _unit_norms(param)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1 - self.rate) * n
        return param * (target / (n + _EPS))


@register_serde
@dataclass
class NonNegativeConstraint(LayerConstraint):
    def apply(self, param):
        return jnp.maximum(param, 0.0)


@register_serde
@dataclass
class UnitNormConstraint(LayerConstraint):
    def apply(self, param):
        return param / (_unit_norms(param) + _EPS)
