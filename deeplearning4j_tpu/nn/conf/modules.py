"""Reusable graph-construction blocks.

Reference ``nn/conf/module/GraphBuilderModule.java``: a unit that appends a
named sub-graph of layers to a GraphBuilder and returns the output vertex
name.  The zoo's conv/inception/residual helpers follow this contract; the
public classes here let users compose the same blocks in their own graphs.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..layers.convolution import ConvolutionLayer, SubsamplingLayer
from ..layers.feedforward import ActivationLayer
from ..layers.normalization import BatchNormalization
from .computation_graph import ElementWiseVertex, GraphBuilder, MergeVertex

__all__ = ["GraphBuilderModule", "ConvBnBlock", "ResidualBlock",
           "InceptionBlock"]


class GraphBuilderModule:
    """add_layers(builder, name, *inputs) -> output vertex name (reference
    ``GraphBuilderModule.addLayers``)."""

    def add_layers(self, g: GraphBuilder, name: str, *inputs: str) -> str:
        raise NotImplementedError


class ConvBnBlock(GraphBuilderModule):
    """conv → batchnorm(+activation) (the zoo's conv_bn unit)."""

    def __init__(self, n_out: int, kernel: Tuple[int, int] = (3, 3),
                 stride: Tuple[int, int] = (1, 1), activation: str = "relu",
                 mode: str = "same"):
        self.n_out = n_out
        self.kernel = kernel
        self.stride = stride
        self.activation = activation
        self.mode = mode

    def add_layers(self, g: GraphBuilder, name: str, *inputs: str) -> str:
        g.add_layer(f"{name}_conv", ConvolutionLayer(
            n_out=self.n_out, kernel_size=self.kernel, stride=self.stride,
            convolution_mode=self.mode, activation="identity",
            has_bias=False), *inputs)
        g.add_layer(f"{name}_bn",
                    BatchNormalization(activation=self.activation),
                    f"{name}_conv")
        return f"{name}_bn"


class ResidualBlock(GraphBuilderModule):
    """Bottleneck residual unit (ResNet50's building block): 1x1 → 3x3 →
    1x1 with an identity or projected shortcut and a post-add ReLU."""

    def __init__(self, filters: Tuple[int, int, int],
                 stride: Tuple[int, int] = (1, 1), project: bool = False):
        self.filters = filters
        self.stride = stride
        self.project = project

    def add_layers(self, g: GraphBuilder, name: str, *inputs: str) -> str:
        f1, f2, f3 = self.filters
        inp = inputs[0]
        x = ConvBnBlock(f1, (1, 1), self.stride).add_layers(g, f"{name}_a",
                                                            inp)
        x = ConvBnBlock(f2, (3, 3)).add_layers(g, f"{name}_b", x)
        x = ConvBnBlock(f3, (1, 1), activation="identity").add_layers(
            g, f"{name}_c", x)
        if self.project:
            sc = ConvBnBlock(f3, (1, 1), self.stride,
                             activation="identity").add_layers(
                g, f"{name}_sc", inp)
        else:
            sc = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"


class InceptionBlock(GraphBuilderModule):
    """GoogLeNet inception unit: 1x1 / 3x3(reduced) / 5x5(reduced) /
    pool-proj branches concatenated on channels."""

    def __init__(self, c1: int, c3r: int, c3: int, c5r: int, c5: int,
                 pool_proj: int):
        self.c1, self.c3r, self.c3 = c1, c3r, c3
        self.c5r, self.c5, self.pool_proj = c5r, c5, pool_proj

    def add_layers(self, g: GraphBuilder, name: str, *inputs: str) -> str:
        inp = inputs[0]
        b1 = ConvBnBlock(self.c1, (1, 1)).add_layers(g, f"{name}_b1", inp)
        r3 = ConvBnBlock(self.c3r, (1, 1)).add_layers(g, f"{name}_b3r", inp)
        b3 = ConvBnBlock(self.c3, (3, 3)).add_layers(g, f"{name}_b3", r3)
        r5 = ConvBnBlock(self.c5r, (1, 1)).add_layers(g, f"{name}_b5r", inp)
        b5 = ConvBnBlock(self.c5, (5, 5)).add_layers(g, f"{name}_b5", r5)
        g.add_layer(f"{name}_pool", SubsamplingLayer(
            pooling_type="max", kernel_size=(3, 3), stride=(1, 1),
            convolution_mode="same"), inp)
        bp = ConvBnBlock(self.pool_proj, (1, 1)).add_layers(
            g, f"{name}_bp", f"{name}_pool")
        g.add_vertex(f"{name}_concat", MergeVertex(), b1, b3, b5, bp)
        return f"{name}_concat"
