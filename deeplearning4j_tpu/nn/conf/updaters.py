"""Updater (optimizer) configurations.

Covers the reference's ``nn/conf/Updater.java:11`` enum — SGD, ADAM, ADAMAX,
ADADELTA, NESTEROVS, NADAM, ADAGRAD, RMSPROP, AMSGRAD, NONE — as serializable
dataclasses resolving to optax gradient transformations.  The reference applies
updater math per contiguous ``UpdaterBlock`` over a flat param view
(``nn/updater/BaseMultiLayerUpdater.java:64-138``); the TPU-native equivalent is
a per-leaf optax transform over the param pytree — XLA fuses the whole update
into one program, and param donation gives the in-place semantics the flat view
existed for.

Per-layer updater overrides (DL4J allows an updater per layer config) are
supported via ``optax.multi_transform`` in the network builder.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union

import optax

from ...utils.serde import register_serde
from .schedules import Schedule, resolve


@dataclass
class UpdaterConf:
    """Base: learning rate may be a float or a Schedule."""
    learning_rate: Union[float, Schedule, None] = None

    def _lr(self, default=1e-3):
        if self.learning_rate is None:
            return default
        sched = resolve(self.learning_rate)
        from .schedules import FixedSchedule
        if isinstance(sched, FixedSchedule):
            return sched.value_
        return sched.as_optax()

    def to_optax(self) -> optax.GradientTransformation:  # pragma: no cover
        raise NotImplementedError

    @property
    def has_state(self) -> bool:
        return True


@register_serde
@dataclass
class Sgd(UpdaterConf):
    def to_optax(self):
        return optax.sgd(self._lr(1e-1))

    @property
    def has_state(self):
        return False


@register_serde
@dataclass
class Nesterovs(UpdaterConf):
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self._lr(1e-1), momentum=self.momentum, nesterov=True)


@register_serde
@dataclass
class Adam(UpdaterConf):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(self._lr(1e-3), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_serde
@dataclass
class AdaMax(UpdaterConf):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(self._lr(1e-3), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_serde
@dataclass
class Nadam(UpdaterConf):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.nadam(self._lr(1e-3), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_serde
@dataclass
class AmsGrad(UpdaterConf):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.amsgrad(self._lr(1e-3), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_serde
@dataclass
class AdaDelta(UpdaterConf):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        # reference AdaDelta has no learning rate (lr=1)
        return optax.adadelta(self._lr(1.0), rho=self.rho, eps=self.epsilon)


@register_serde
@dataclass
class AdaGrad(UpdaterConf):
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(self._lr(1e-1), eps=self.epsilon)


@register_serde
@dataclass
class RmsProp(UpdaterConf):
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(self._lr(1e-1), decay=self.rms_decay, eps=self.epsilon)


@register_serde
@dataclass
class NoOp(UpdaterConf):
    """Updater.NONE — gradients are not applied (frozen params)."""

    def to_optax(self):
        return optax.set_to_zero()

    @property
    def has_state(self):
        return False


@register_serde
@dataclass
class AdamW(UpdaterConf):
    """Decoupled weight decay Adam (modern extension beyond the reference)."""
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.01

    def to_optax(self):
        return optax.adamw(self._lr(1e-3), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@register_serde
@dataclass
class Lion(UpdaterConf):
    """Lion optimizer (modern extension; efficient on TPU — sign updates)."""
    beta1: float = 0.9
    beta2: float = 0.99
    weight_decay: float = 0.0

    def to_optax(self):
        return optax.lion(self._lr(1e-4), b1=self.beta1, b2=self.beta2,
                          weight_decay=self.weight_decay)


def by_name(name: str, learning_rate=None, **kwargs) -> UpdaterConf:
    """Resolve a DL4J Updater enum name to a config instance."""
    table = {
        "sgd": Sgd, "adam": Adam, "adamax": AdaMax, "adadelta": AdaDelta,
        "nesterovs": Nesterovs, "nadam": Nadam, "adagrad": AdaGrad,
        "rmsprop": RmsProp, "none": NoOp, "amsgrad": AmsGrad,
        "adamw": AdamW, "lion": Lion,
    }
    cls = table.get(name.lower())
    if cls is None:
        raise ValueError(f"unknown updater '{name}'; available: {sorted(table)}")
    return cls(learning_rate=learning_rate, **kwargs)
