"""ComputationGraph configuration: vertices + fluent GraphBuilder.

TPU-native analogue of ``nn/conf/ComputationGraphConfiguration.java:59`` and
the vertex configs in ``nn/conf/graph/`` (ElementWiseVertex, MergeVertex,
SubsetVertex, StackVertex/UnstackVertex, ScaleVertex/ShiftVertex,
L2NormalizeVertex, L2Vertex, ReshapeVertex, PreprocessorVertex, PoolHelper,
plus the rnn vertices ``nn/conf/graph/rnn/LastTimeStepVertex`` and
``DuplicateToTimeSeriesVertex``).

Design: the graph is data — a dict of named vertex configs plus an input-name
map.  Topological order and all shapes (InputTypes) are resolved at
configuration time, so the runtime trace is a static unrolled DAG that XLA
sees as one fused program (the reference instead walks the topological order
per-call in Java, ``nn/graph/ComputationGraph.java:1191``).

Every vertex is a pure function ``apply(variables, inputs, ...)`` — no
in-place epsilon accumulation; fan-in gradients are summed by jax.grad
automatically (the reference hand-accumulates epsilons at fan-in vertices).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ...utils import serde
from ...utils.serde import register_serde
from .input_type import InputType
from .multi_layer import _auto_preprocessor
from .preprocessors import InputPreProcessor
from ..layers.base import BaseLayerConf, LayerConf

Array = jax.Array


# ---------------------------------------------------------------------------
# vertex configs
# ---------------------------------------------------------------------------

@dataclass
class GraphVertexConf:
    """Base vertex (reference ``nn/conf/graph/GraphVertex.java``)."""

    def n_inputs(self) -> Tuple[int, int]:
        """(min, max) accepted input count; max=-1 means unbounded."""
        return (1, 1)

    def output_type(self, itypes: List[InputType]) -> InputType:
        return itypes[0]

    def has_params(self) -> bool:
        return False

    def init(self, key, itypes: List[InputType]) -> Dict[str, Any]:
        return {"params": {}, "state": {}}

    def apply(self, variables, inputs: List[Array], *, train=False, key=None,
              masks: Optional[List[Optional[Array]]] = None
              ) -> Tuple[Array, Dict[str, Array]]:
        raise NotImplementedError

    def feed_forward_mask(self, masks: List[Optional[Array]],
                          inputs: Optional[List[Array]] = None
                          ) -> Optional[Array]:
        """Propagate masks; ``inputs`` are the runtime input activations (for
        vertices whose mask shape depends on input shapes)."""
        for m in masks:
            if m is not None:
                return m
        return None

    def regularization_score(self, params) -> Array:
        return jnp.zeros((), jnp.float32)


@register_serde
@dataclass
class LayerVertex(GraphVertexConf):
    """Wraps a LayerConf (reference ``nn/conf/graph/LayerVertex.java``)."""
    layer: LayerConf = None
    preprocessor: Optional[InputPreProcessor] = None

    def output_type(self, itypes):
        it = itypes[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)

    def has_params(self) -> bool:
        return self.layer.has_params()

    def init(self, key, itypes):
        it = itypes[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.init(key, it)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if self.preprocessor is not None:
            x = self.preprocessor.pre_process(x, mask)
            if mask is not None:
                mask = self.preprocessor.feed_forward_mask(mask, None)
        return self.layer.apply(variables, x, train=train, key=key, mask=mask)

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None):
        if self.preprocessor is not None:
            x = self.preprocessor.pre_process(x, mask)
            if mask is not None:
                mask = self.preprocessor.feed_forward_mask(mask, None)
        return self.layer.compute_loss(variables, x, labels, train=train,
                                       key=key, mask=mask)

    def feed_forward_mask(self, masks, inputs=None):
        mask = masks[0] if masks else None
        if mask is not None and self.preprocessor is not None:
            mask = self.preprocessor.feed_forward_mask(mask, None)
        if mask is not None:
            mask = self.layer.feed_forward_mask(mask, None)
        return mask

    def regularization_score(self, params) -> Array:
        return self.layer.regularization_score(params)


@register_serde
@dataclass
class ElementWiseVertex(GraphVertexConf):
    """Pointwise combine: Add/Subtract/Product/Average/Max
    (reference ``nn/conf/graph/ElementWiseVertex.java``)."""
    op: str = "add"

    def n_inputs(self):
        return (2, 2) if self.op == "subtract" else (2, -1)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"unknown elementwise op '{self.op}'")
        return out, variables.get("state", {})


@register_serde
@dataclass
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature axis — last axis for FF/RNN/CNN(NHWC)
    (reference ``nn/conf/graph/MergeVertex.java`` concatenates dim 1 in NCHW;
    NHWC's channel-minor layout makes that the last axis here)."""

    def n_inputs(self):
        return (1, -1)

    def output_type(self, itypes):
        first = itypes[0]
        if first.kind == "ff":
            return InputType.feed_forward(sum(t.size for t in itypes))
        if first.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in itypes), first.timesteps)
        if first.kind == "cnn":
            return InputType.convolutional(first.height, first.width,
                                           sum(t.channels for t in itypes))
        raise ValueError(f"MergeVertex: unsupported input kind {first.kind}")

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        return jnp.concatenate(inputs, axis=-1), variables.get("state", {})


@register_serde
@dataclass
class SubsetVertex(GraphVertexConf):
    """Feature-range slice [from, to] inclusive
    (reference ``nn/conf/graph/SubsetVertex.java``)."""
    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, itypes):
        n = self.to_idx - self.from_idx + 1
        t = itypes[0]
        if t.kind == "ff":
            return InputType.feed_forward(n)
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        raise ValueError(t.kind)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        return (jax.lax.slice_in_dim(x, self.from_idx, self.to_idx + 1, axis=x.ndim - 1),
                variables.get("state", {}))


@register_serde
@dataclass
class StackVertex(GraphVertexConf):
    """Concatenate along the BATCH axis (reference ``StackVertex.java`` —
    used for sharing one layer across several inputs)."""

    def n_inputs(self):
        return (1, -1)

    def output_type(self, itypes):
        return itypes[0]

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        return jnp.concatenate(inputs, axis=0), variables.get("state", {})

    def feed_forward_mask(self, masks, inputs=None):
        if all(m is None for m in masks):
            return None
        # unmasked inputs contribute all-ones (reference semantics): dropping
        # the combined mask would silently unmask the padded inputs
        proto = next(m for m in masks if m is not None)
        out = []
        for i, m in enumerate(masks):
            if m is None:
                if inputs is None:
                    raise ValueError(
                        "StackVertex: mixed masked/unmasked inputs need "
                        "runtime shapes to synthesize all-ones masks")
                out.append(jnp.ones((inputs[i].shape[0],) + proto.shape[1:],
                                    proto.dtype))
            else:
                out.append(m)
        return jnp.concatenate(out, axis=0)


@register_serde
@dataclass
class UnstackVertex(GraphVertexConf):
    """Inverse of StackVertex: take batch-slab ``from_idx`` of ``stack_size``
    equal slabs (reference ``UnstackVertex.java``)."""
    from_idx: int = 0
    stack_size: int = 1

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return (jax.lax.slice_in_dim(x, self.from_idx * step,
                                     (self.from_idx + 1) * step, axis=0),
                variables.get("state", {}))

    def feed_forward_mask(self, masks, inputs=None):
        m = masks[0] if masks else None
        if m is None:
            return None
        step = m.shape[0] // self.stack_size
        return jax.lax.slice_in_dim(m, self.from_idx * step,
                                    (self.from_idx + 1) * step, axis=0)


@register_serde
@dataclass
class ScaleVertex(GraphVertexConf):
    """Multiply by a fixed scalar (reference ``ScaleVertex.java``)."""
    scale_factor: float = 1.0

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        return inputs[0] * self.scale_factor, variables.get("state", {})


@register_serde
@dataclass
class ShiftVertex(GraphVertexConf):
    """Add a fixed scalar (reference ``ShiftVertex.java``)."""
    shift_factor: float = 0.0

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        return inputs[0] + self.shift_factor, variables.get("state", {})


@register_serde
@dataclass
class L2NormalizeVertex(GraphVertexConf):
    """x / ||x||_2 per example (reference ``L2NormalizeVertex.java``)."""
    eps: float = 1e-8

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (norm + self.eps), variables.get("state", {})


@register_serde
@dataclass
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two activations → [batch, 1]
    (reference ``L2Vertex.java``)."""
    eps: float = 1e-8

    def n_inputs(self):
        return (2, 2)

    def output_type(self, itypes):
        return InputType.feed_forward(1)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        a = inputs[0].reshape(inputs[0].shape[0], -1)
        b = inputs[1].reshape(inputs[1].shape[0], -1)
        d = a - b
        # eps inside sqrt keeps the gradient finite at d == 0
        out = jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)
        return out, variables.get("state", {})


@register_serde
@dataclass
class ReshapeVertex(GraphVertexConf):
    """Reshape per example; shape excludes batch dim
    (reference ``ReshapeVertex.java``)."""
    shape: List[int] = field(default_factory=list)

    def output_type(self, itypes):
        s = self.shape
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        raise ValueError(f"ReshapeVertex: bad shape {s}")

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape)), variables.get("state", {})


@register_serde
@dataclass
class PreprocessorVertex(GraphVertexConf):
    """Standalone InputPreProcessor as a vertex (reference
    ``PreprocessorVertex.java``)."""
    preprocessor: InputPreProcessor = None

    def output_type(self, itypes):
        return self.preprocessor.output_type(itypes[0])

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        mask = masks[0] if masks else None
        return self.preprocessor.pre_process(inputs[0], mask), variables.get("state", {})


@register_serde
@dataclass
class PoolHelperVertex(GraphVertexConf):
    """Strip first row+column of a CNN activation (reference
    ``PoolHelperVertex.java`` — compatibility shim for imported GoogLeNet)."""

    def output_type(self, itypes):
        t = itypes[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        return inputs[0][:, 1:, 1:, :], variables.get("state", {})


@register_serde
@dataclass
class LastTimeStepVertex(GraphVertexConf):
    """RNN [b,t,f] → FF [b,f] at the last *unmasked* step (reference
    ``nn/conf/graph/rnn/LastTimeStepVertex.java``).  ``mask_input`` names the
    network input whose mask determines sequence lengths."""
    mask_input: Optional[str] = None

    def output_type(self, itypes):
        t = itypes[0]
        return InputType.feed_forward(t.size)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            out = x[:, -1, :]
        else:
            # index of last nonzero mask entry per example
            idx = x.shape[1] - 1 - jnp.argmax(mask[:, ::-1], axis=1)
            out = jax.vmap(lambda seq, i: seq[i])(x, idx.astype(jnp.int32))
        return out, variables.get("state", {})

    def feed_forward_mask(self, masks, inputs=None):
        return None  # time axis consumed


@register_serde
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """FF [b,f] → RNN [b,t,f] by repetition; t taken from the named network
    input (reference ``rnn/DuplicateToTimeSeriesVertex.java``)."""
    ts_input: str = ""
    timesteps: int = -1  # resolved from ts_input's InputType at build time

    def n_inputs(self):
        # optional second input: the time-series whose length to copy (kept
        # as a real graph edge so the shape is dynamic-batch-safe)
        return (1, 2)

    def output_type(self, itypes):
        t = itypes[0]
        return InputType.recurrent(t.size, self.timesteps)

    def apply(self, variables, inputs, *, train=False, key=None, masks=None):
        x = inputs[0]      # [b, f]
        t = inputs[1].shape[1] if len(inputs) > 1 else self.timesteps
        if t is None or t < 0:
            raise ValueError(
                "DuplicateToTimeSeriesVertex needs static timesteps or the "
                "ts_input wired as a second graph input")
        return jnp.repeat(x[:, None, :], t, axis=1), variables.get("state", {})


# ---------------------------------------------------------------------------
# configuration + builder
# ---------------------------------------------------------------------------

@register_serde
@dataclass
class ComputationGraphConfiguration:
    """The graph as data (reference ``ComputationGraphConfiguration.java:59``)."""
    vertices: Dict[str, GraphVertexConf] = field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    input_types: List[Optional[InputType]] = field(default_factory=list)
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    defaults: Dict[str, Any] = field(default_factory=dict)
    seed: int = 12345
    # resolved:
    topological_order: List[str] = field(default_factory=list)
    vertex_input_types: Dict[str, List[Any]] = field(default_factory=dict)

    # ---- serde ----
    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        conf = serde.from_json(s)
        assert isinstance(conf, ComputationGraphConfiguration)
        return conf

    def to_yaml(self) -> str:
        return serde.to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        return serde.from_yaml(s)

    # ---- resolution ----
    def topo_sort(self) -> List[str]:
        """Kahn's algorithm (reference topologicalSortOrder :1191)."""
        indeg = {}
        children: Dict[str, List[str]] = {}
        for name, ins in self.vertex_inputs.items():
            indeg[name] = 0
            for src in ins:
                if src in self.vertices:
                    indeg[name] += 1
                    children.setdefault(src, []).append(name)
                elif src not in self.network_inputs:
                    raise ValueError(
                        f"vertex '{name}' input '{src}' is neither a vertex "
                        "nor a network input")
        ready = sorted(n for n, d in indeg.items() if d == 0)
        order = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in children.get(n, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"graph has a cycle involving {sorted(cyc)}")
        return order

    def resolve(self) -> None:
        for name in self.network_outputs:
            if name not in self.vertices:
                raise ValueError(f"network output '{name}' is not a vertex")
        from .multi_layer import validate_layer_names
        for v in self.vertices.values():
            lc = getattr(v, "layer", None)
            # duck-typed: wrapper layers delegate to the layer they wrap
            if hasattr(lc, "apply_global_defaults"):
                lc.apply_global_defaults(self.defaults)
            validate_layer_names(lc)
        self.topological_order = self.topo_sort()

        # input types per network input
        it_by_name: Dict[str, Optional[InputType]] = {}
        for i, n in enumerate(self.network_inputs):
            it_by_name[n] = (self.input_types[i]
                             if i < len(self.input_types) else None)

        self.vertex_input_types = {}
        for name in self.topological_order:
            v = self.vertices[name]
            ins = self.vertex_inputs[name]
            itypes = [it_by_name.get(src) for src in ins]
            lo, hi = v.n_inputs()
            if len(ins) < lo or (hi != -1 and len(ins) > hi):
                raise ValueError(
                    f"vertex '{name}' takes {lo}..{'∞' if hi == -1 else hi} "
                    f"inputs, got {len(ins)}")
            if all(t is not None for t in itypes):
                if isinstance(v, LayerVertex):
                    if v.preprocessor is None:
                        v.preprocessor = _auto_preprocessor(itypes[0], v.layer)
                    it = itypes[0]
                    if v.preprocessor is not None:
                        it = v.preprocessor.output_type(it)
                    v.layer.set_n_in(it, override=False)
                if isinstance(v, DuplicateToTimeSeriesVertex):
                    ref = it_by_name.get(v.ts_input)
                    if ref is not None:
                        v.timesteps = ref.timesteps
                self.vertex_input_types[name] = itypes
                it_by_name[name] = v.output_type(itypes)
            else:
                self.vertex_input_types[name] = itypes
                it_by_name[name] = None

    def vertex_output_type(self, name: str) -> Optional[InputType]:
        itypes = self.vertex_input_types.get(name)
        if itypes is None or any(t is None for t in itypes):
            return None
        return self.vertices[name].output_type(itypes)


class GraphBuilder:
    """Fluent builder (reference ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, defaults: Dict[str, Any] = None, seed: int = 12345):
        self._defaults = dict(defaults or {})
        self._seed = seed
        self._vertices: Dict[str, GraphVertexConf] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._input_types: List[Optional[InputType]] = []
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *itypes: InputType) -> "GraphBuilder":
        self._input_types = list(itypes)
        return self

    def add_layer(self, name: str, layer: LayerConf, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None) -> "GraphBuilder":
        if layer.name is None:
            layer.name = name
        return self.add_vertex(name, LayerVertex(layer=layer,
                                                 preprocessor=preprocessor),
                               *inputs)

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs: str
                   ) -> "GraphBuilder":
        if name in self._vertices:
            raise ValueError(f"duplicate vertex name '{name}'")
        if not inputs:
            raise ValueError(f"vertex '{name}' needs at least one input")
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, t: str, fwd: int = 20, back: int = 20) -> "GraphBuilder":
        self._backprop_type = t
        self._tbptt_fwd = fwd
        self._tbptt_back = back
        return self

    def build(self) -> ComputationGraphConfiguration:
        conf = ComputationGraphConfiguration(
            vertices=self._vertices,
            vertex_inputs=self._vertex_inputs,
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            defaults=dict(self._defaults),
            seed=self._seed,
        )
        conf.resolve()
        return conf
