"""Sampling distributions for WeightInit.DISTRIBUTION.

Analogue of the reference's ``nn/conf/distribution/`` package (Normal, Uniform,
Binomial, LogNormal, TruncatedNormal, Orthogonal, Constant) as serializable
dataclasses with a ``sample`` method over a JAX PRNG key.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Type

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde

_DIST_REGISTRY: Dict[str, Type["Distribution"]] = {}


def register_distribution(cls):
    _DIST_REGISTRY[cls.__name__] = cls
    return register_serde(cls)


@dataclass
class Distribution:
    def sample(self, key, shape):  # pragma: no cover - abstract
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@dist"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _DIST_REGISTRY[d.pop("@dist")]
        return cls(**d)


@register_distribution
@dataclass
class NormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape):
        return self.mean + self.std * jax.random.normal(key, shape)


@register_distribution
@dataclass
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, key, shape):
        return jax.random.uniform(key, shape, minval=self.lower, maxval=self.upper)


@register_distribution
@dataclass
class BinomialDistribution(Distribution):
    trials: int = 1
    prob: float = 0.5

    def sample(self, key, shape):
        return jnp.sum(
            jax.random.bernoulli(key, self.prob, (self.trials,) + tuple(shape)).astype(jnp.float32),
            axis=0)


@register_distribution
@dataclass
class LogNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape):
        return jnp.exp(self.mean + self.std * jax.random.normal(key, shape))


@register_distribution
@dataclass
class TruncatedNormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, key, shape):
        return self.mean + self.std * jax.random.truncated_normal(key, -2.0, 2.0, shape)


@register_distribution
@dataclass
class OrthogonalDistribution(Distribution):
    gain: float = 1.0

    def sample(self, key, shape):
        if len(shape) < 2:
            raise ValueError("orthogonal requires >=2d shape")
        rows = shape[0]
        cols = 1
        for d in shape[1:]:
            cols *= d
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return self.gain * q[:rows, :cols].reshape(shape)


@register_distribution
@dataclass
class ConstantDistribution(Distribution):
    value: float = 0.0

    def sample(self, key, shape):
        return jnp.full(shape, self.value)
