"""Network configuration: global defaults + sequential layer stack.

Analogue of ``nn/conf/NeuralNetConfiguration.java:78`` (Builder + ListBuilder)
and ``nn/conf/MultiLayerConfiguration.java:55``.  The builder resolves, at
configuration time: global-default inheritance into each layer, static shape
inference via InputType, automatic preprocessor insertion between layer
families, and n_in inference — all before a single array exists, exactly as
the reference does, which also guarantees jit-compatible static shapes.

JSON/YAML round-trip via utils.serde mirrors ``toJson/fromJson``
(``MultiLayerConfiguration.java:120,138``).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ...utils import serde
from ...utils.serde import register_serde
from .input_type import InputType
from .preprocessors import (CnnFlatToCnnPreProcessor, CnnToFeedForwardPreProcessor,
                            CnnToRnnPreProcessor, FeedForwardToRnnPreProcessor,
                            InputPreProcessor, RnnToCnnPreProcessor,
                            RnnToFeedForwardPreProcessor)
from ..layers.base import BaseLayerConf, LayerConf


def validate_layer_names(lc, _seen: Optional[set] = None) -> None:
    """Fail at CONFIG time on unknown activation/loss names, not at the
    first fit() (the reference validates configs up front —
    ``nn/conf/layers/LayerValidation.java``).  Recurses through wrapper
    layers (Bidirectional ``fwd``, Frozen/LastTimeStep ``underlying``,
    graph LayerVertex ``layer``) to any depth; a visited-id set guards
    against config cycles."""
    if lc is None:
        return
    if _seen is None:
        _seen = set()
    if id(lc) in _seen:
        return
    _seen.add(id(lc))
    from ..activations import get as _get_act
    from ..losses import get as _get_loss
    act = getattr(lc, "activation", None)
    if isinstance(act, str):
        _get_act(act)
    loss = getattr(lc, "loss", None)
    if isinstance(loss, str):
        _get_loss(loss)
    for attr in ("fwd", "underlying", "layer"):
        inner = getattr(lc, attr, None)
        if inner is not lc and isinstance(inner, LayerConf):
            validate_layer_names(inner, _seen)


def _auto_preprocessor(prev: InputType, layer: LayerConf) -> Optional[InputPreProcessor]:
    """Insert a reshape adapter when layer families change
    (reference ``nn/conf/layers/InputTypeUtil.java`` + per-layer
    getPreProcessorForInputType)."""
    want = getattr(layer, "INPUT_KIND", "any")
    if want == "any" or prev.kind == want:
        return None
    if want == "ff":
        if prev.kind == "cnn":
            return CnnToFeedForwardPreProcessor(prev.height, prev.width, prev.channels)
        if prev.kind == "cnnflat":
            return None  # already flat
        if prev.kind == "rnn":
            return RnnToFeedForwardPreProcessor()
    elif want == "cnn":
        if prev.kind == "cnnflat":
            return CnnFlatToCnnPreProcessor(prev.height, prev.width, prev.channels)
        if prev.kind == "ff":
            raise ValueError(
                f"cannot infer CNN dims from FF input for layer '{layer.name}'; "
                "add an explicit FeedForwardToCnnPreProcessor")
    elif want == "rnn":
        if prev.kind == "ff":
            return FeedForwardToRnnPreProcessor()
        if prev.kind == "cnn":
            return CnnToRnnPreProcessor(prev.height, prev.width, prev.channels)
    raise ValueError(
        f"no automatic preprocessor from {prev.kind} input to '{want}' layer "
        f"'{layer.name}'")


@register_serde
@dataclass
class MultiLayerConfiguration:
    layers: List[LayerConf] = field(default_factory=list)
    input_type: Optional[InputType] = None
    # int-keyed dict serializes with str keys in json; normalize on access
    input_preprocessors: Dict[str, InputPreProcessor] = field(default_factory=dict)
    backprop_type: str = "standard"           # "standard" | "tbptt"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    defaults: Dict[str, Any] = field(default_factory=dict)
    seed: int = 12345
    # resolved by build():
    layer_input_types: List[InputType] = field(default_factory=list)

    # ---- serde --------------------------------------------------------------
    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        conf = serde.from_json(s)
        assert isinstance(conf, MultiLayerConfiguration)
        return conf

    def to_yaml(self) -> str:
        return serde.to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        return serde.from_yaml(s)

    # ---- shape resolution ---------------------------------------------------
    def preprocessor(self, i: int) -> Optional[InputPreProcessor]:
        return self.input_preprocessors.get(str(i))

    def resolve(self) -> None:
        """Apply defaults, insert preprocessors, infer n_in, record itypes."""
        for lc in self.layers:
            # duck-typed: wrappers (Bidirectional, LastTimeStep, Frozen)
            # delegate defaults to the layer they wrap
            if hasattr(lc, "apply_global_defaults"):
                lc.apply_global_defaults(self.defaults)
            validate_layer_names(lc)
        self.layer_input_types = []
        itype = self.input_type
        for i, lc in enumerate(self.layers):
            if itype is not None:
                if str(i) not in self.input_preprocessors:
                    pp = _auto_preprocessor(itype, lc)
                    if pp is not None:
                        self.input_preprocessors[str(i)] = pp
                pp = self.preprocessor(i)
                if pp is not None:
                    itype = pp.output_type(itype)
                lc.set_n_in(itype, override=False)
                self.layer_input_types.append(itype)
                itype = lc.output_type(itype)
            else:
                # no declared input type (reference: user sets nIn explicitly);
                # chain output types forward once a layer determines its own.
                self.layer_input_types.append(None)
                try:
                    itype = lc.output_type(itype)
                except Exception:
                    itype = None


class ListBuilder:
    """Fluent layer-stack builder (reference NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, defaults: Dict[str, Any], seed: int):
        self._defaults = defaults
        self._seed = seed
        self._layers: List[LayerConf] = []
        self._input_type: Optional[InputType] = None
        self._preprocessors: Dict[str, InputPreProcessor] = {}
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, conf: LayerConf, index: Optional[int] = None) -> "ListBuilder":
        """Append, or place at ``index`` (reference ListBuilder.layer(int, Layer)
        semantics: set the layer at that position, padding is not allowed)."""
        if conf.name is None:
            conf.name = f"layer{index if index is not None else len(self._layers)}"
        if index is None or index == len(self._layers):
            self._layers.append(conf)
        elif 0 <= index < len(self._layers):
            self._layers[index] = conf
        else:
            raise ValueError(
                f"layer index {index} out of range (have {len(self._layers)} layers)")
        return self

    def set_input_type(self, itype: InputType) -> "ListBuilder":
        self._input_type = itype
        return self

    def input_pre_processor(self, index: int, pp: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[str(index)] = pp
        return self

    def backprop_type(self, t: str, fwd: int = 20, back: int = 20) -> "ListBuilder":
        self._backprop_type = t
        self._tbptt_fwd = fwd
        self._tbptt_back = back
        return self

    def build(self) -> MultiLayerConfiguration:
        conf = MultiLayerConfiguration(
            layers=self._layers,
            input_type=self._input_type,
            input_preprocessors=self._preprocessors,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            defaults=dict(self._defaults),
            seed=self._seed,
        )
        conf.resolve()
        return conf


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()`` fluent API."""

    class Builder:
        def __init__(self):
            self._defaults: Dict[str, Any] = {}
            self._seed = 12345

        # global defaults — each maps onto the same-named reference builder call
        def seed(self, s: int):
            self._seed = int(s)
            return self

        def activation(self, a):
            self._defaults["activation"] = a
            return self

        def weight_init(self, w, dist=None):
            self._defaults["weight_init"] = w
            if dist is not None:
                self._defaults["weight_dist"] = dist
            return self

        def bias_init(self, b: float):
            self._defaults["bias_init"] = float(b)
            return self

        def updater(self, u):
            self._defaults["updater"] = u
            return self

        def bias_updater(self, u):
            self._defaults["bias_updater"] = u
            return self

        def l1(self, v: float):
            self._defaults["l1"] = float(v)
            return self

        def l2(self, v: float):
            self._defaults["l2"] = float(v)
            return self

        def l1_bias(self, v: float):
            self._defaults["l1_bias"] = float(v)
            return self

        def l2_bias(self, v: float):
            self._defaults["l2_bias"] = float(v)
            return self

        def dropout(self, d):
            self._defaults["dropout"] = d
            return self

        def weight_noise(self, wn):
            self._defaults["weight_noise"] = wn
            return self

        def constraints(self, cs):
            self._defaults["constraints"] = cs
            return self

        def gradient_normalization(self, gn, threshold: float = 1.0):
            self._defaults["gradient_normalization"] = gn
            self._defaults["gradient_normalization_threshold"] = float(threshold)
            return self

        def dtype(self, dt: str):
            self._defaults["dtype"] = dt
            return self

        def cache_mode(self, mode: str):
            """Activation memory policy (reference ``nn/conf/CacheMode.java``
            + WorkspaceMode): 'none' (default — XLA's buffer allocator
            manages activations) or 'remat' (``jax.checkpoint`` per layer:
            recompute activations in the backward pass, trading FLOPs for
            HBM — the TPU equivalent of cached workspaces)."""
            if mode not in ("none", "remat"):
                raise ValueError(f"cache_mode must be 'none' or 'remat', "
                                 f"got '{mode}'")
            self._defaults["cache_mode"] = mode
            return self

        def compute_dtype(self, dt: str):
            """Mixed precision: master params/optimizer state stay float32,
            forward+backward run in ``dt`` (normally 'bfloat16' — the TPU
            MXU's native input type).  Normalization statistics are kept
            float32.  The reference has no equivalent (CUDA fp32); this
            is shorthand for :meth:`precision` — use that for loss
            scaling or per-layer overrides."""
            self._defaults["compute_dtype"] = str(dt)
            return self

        def precision(self, policy):
            """First-class mixed-precision policy (``nn/precision``):
            a ``PrecisionPolicy`` instance, or a shorthand string —
            'bfloat16' (bf16 compute / f32 masters, no scaling),
            'float16' (f16 compute with dynamic loss scaling), 'float32'
            (full precision).  BatchNorm and loss/softmax reductions stay
            f32; the policy participates in the compile-cache topology
            signature, so variants never share a trace."""
            from ..precision import PrecisionPolicy, named_policy
            if isinstance(policy, str):
                policy = named_policy(policy)
            if not isinstance(policy, PrecisionPolicy):
                raise ValueError(
                    "precision() takes a PrecisionPolicy or a dtype "
                    f"shorthand string, got {type(policy).__name__}")
            self._defaults["precision"] = policy
            # mirror the legacy knob for consumers that only need the
            # compute dtype (memory reports, zoo model builders)
            if policy.compute_dtype:
                self._defaults["compute_dtype"] = policy.compute_dtype
            return self

        def scan_layers(self, mode):
            """Scan-over-layers control (``nn/scan_layers``): ``False``
            (or ``0``, mirroring ``DL4J_TPU_SCAN_LAYERS=0``) disables for
            this conf, ``True`` uses the process default minimum run
            length (``DL4J_TPU_SCAN_MIN``, default 4), an int >= 2
            overrides the minimum homogeneous-run length."""
            if not isinstance(mode, (bool, int)):
                raise ValueError("scan_layers(True|False|min_run_length)")
            if not isinstance(mode, bool):
                if mode == 0:
                    mode = False       # env-flag parity: 0 means off
                elif mode < 2:
                    raise ValueError(
                        "scan_layers min run length must be >= 2 "
                        "(a 1-layer 'run' cannot scan); use False/0 to "
                        "disable")
            self._defaults["scan_layers"] = mode
            return self

        def optimization_algo(self, algo: str, max_iterations: int = 100):
            """Pick the solver (reference ``OptimizationAlgorithm``):
            'sgd' (default, jitted minibatch path) or the legacy
            full-batch methods 'lbfgs' / 'conjugate_gradient' /
            'line_gradient_descent' (train/solvers.py)."""
            self._defaults["optimization_algo"] = str(algo).lower()
            self._defaults["max_iterations"] = int(max_iterations)
            return self

        def list(self) -> ListBuilder:
            return ListBuilder(self._defaults, self._seed)

        def graph_builder(self):
            from .computation_graph import GraphBuilder
            return GraphBuilder(self._defaults, self._seed)

    @staticmethod
    def builder() -> "NeuralNetConfiguration.Builder":
        return NeuralNetConfiguration.Builder()
