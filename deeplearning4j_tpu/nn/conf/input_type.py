"""Static shape inference — InputType.

TPU-native analogue of ``nn/conf/inputs/InputType.java:43``: every layer config
declares ``output_type(input_type)`` so a whole network's shapes are inferred
*before* any array is allocated.  Under XLA this matters doubly: static shapes
are what let the compiler tile matmuls/convs onto the MXU, so shape inference
here is also the contract that keeps everything jit-compatible.

Kinds:
  - FF(size)                      feed-forward activations  [batch, size]
  - RNN(size, timesteps)          time series               [batch, time, size]   (time-major inside scan)
  - CNN(height, width, channels)  images, NHWC              [batch, h, w, c]
  - CNNFlat(height, width, channels)  flattened images      [batch, h*w*c]
  - CNN3D(d, h, w, channels)      volumetric, NDHWC

Note the reference uses NCHW ([mb, c, h, w]); we use NHWC which is the
TPU-preferred layout (channel-minor feeds the MXU lanes directly).
"""
from __future__ import annotations

from dataclasses import dataclass, asdict
from typing import Optional, Tuple

from ...utils.serde import register_serde


@register_serde
@dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnnflat" | "cnn3d"
    size: int = 0            # ff/rnn feature size
    timesteps: int = -1      # rnn; -1 = variable
    height: int = 0
    width: int = 0
    depth: int = 0           # cnn3d
    channels: int = 0

    # ---- constructors ------------------------------------------------------
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: int = -1) -> "InputType":
        return InputType("rnn", size=int(size), timesteps=int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnnflat", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn3d", depth=int(depth), height=int(height), width=int(width),
                         channels=int(channels))

    # ---- helpers -----------------------------------------------------------
    def flat_size(self) -> int:
        """Total per-example element count (InputType.arrayElementsPerExample)."""
        if self.kind == "ff":
            return self.size
        if self.kind == "rnn":
            if self.timesteps < 0:
                raise ValueError("variable-length RNN input has no static flat size")
            return self.size * self.timesteps
        if self.kind in ("cnn", "cnnflat"):
            return self.height * self.width * self.channels
        if self.kind == "cnn3d":
            return self.depth * self.height * self.width * self.channels
        raise ValueError(self.kind)

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        """Array shape with batch dim (−1 placeholder allowed)."""
        if self.kind == "ff":
            return (batch, self.size)
        if self.kind == "rnn":
            return (batch, self.timesteps, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnnflat":
            return (batch, self.height * self.width * self.channels)
        if self.kind == "cnn3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    def to_dict(self):
        return asdict(self)

    @staticmethod
    def from_dict(d) -> "InputType":
        return InputType(**d)

    @staticmethod
    def infer(x, is_recurrent: bool = False) -> "InputType":
        """Best-effort inference from an array (InputType.inferInputType)."""
        if x.ndim == 2:
            if is_recurrent:
                raise ValueError("2d array cannot be recurrent input")
            return InputType.feed_forward(x.shape[1])
        if x.ndim == 3:
            return InputType.recurrent(x.shape[2], x.shape[1])
        if x.ndim == 4:
            return InputType.convolutional(x.shape[1], x.shape[2], x.shape[3])
        if x.ndim == 5:
            return InputType.convolutional_3d(x.shape[1], x.shape[2], x.shape[3], x.shape[4])
        raise ValueError(f"cannot infer input type from shape {x.shape}")
