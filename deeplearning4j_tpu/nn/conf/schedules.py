"""Learning-rate (and generally hyperparameter) schedules.

Analogue of the reference's ``nn/conf/LearningRatePolicy.java`` + nd4j
``ISchedule`` family (Step, Poly, Exponential, Inverse, Sigmoid, Cycle, Map).
Each schedule is a serializable dataclass with ``value(iteration, epoch)``;
``as_optax`` converts to an optax-compatible ``fn(count)`` for use inside the
jitted update (schedules are computed on-device from the step counter, so the
whole update stays one fused XLA program).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

import jax.numpy as jnp

from ...utils.serde import register_serde


@dataclass
class Schedule:
    def value(self, iteration, epoch=0):  # pragma: no cover - abstract
        raise NotImplementedError

    def as_optax(self):
        return lambda count: self.value(count)


@register_serde
@dataclass
class FixedSchedule(Schedule):
    value_: float = 0.001

    def value(self, iteration, epoch=0):
        return self.value_


@register_serde
@dataclass
class StepSchedule(Schedule):
    """lr * decay_rate^floor(iter / step)."""
    initial_value: float = 0.001
    decay_rate: float = 0.1
    step: float = 1000.0

    def value(self, iteration, epoch=0):
        return self.initial_value * self.decay_rate ** jnp.floor(iteration / self.step)


@register_serde
@dataclass
class ExponentialSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.99

    def value(self, iteration, epoch=0):
        return self.initial_value * self.gamma ** iteration


@register_serde
@dataclass
class InverseSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.001
    power: float = 2.0

    def value(self, iteration, epoch=0):
        return self.initial_value / (1 + self.gamma * iteration) ** self.power


@register_serde
@dataclass
class PolySchedule(Schedule):
    initial_value: float = 0.001
    power: float = 2.0
    max_iter: int = 10000

    def value(self, iteration, epoch=0):
        frac = jnp.clip(iteration / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1 - frac) ** self.power


@register_serde
@dataclass
class SigmoidSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.01
    step_size: int = 1000

    def value(self, iteration, epoch=0):
        return self.initial_value / (1 + jnp.exp(self.gamma * (iteration - self.step_size)))


@register_serde
@dataclass
class MapSchedule(Schedule):
    """Piecewise-constant by iteration: {0: lr0, 1000: lr1, ...}."""
    values: Dict[int, float] = field(default_factory=dict)

    def value(self, iteration, epoch=0):
        keys = sorted(int(k) for k in self.values)
        out = jnp.asarray(self.values[keys[0]] if keys else 0.0)
        for k in keys:
            out = jnp.where(iteration >= k, self.values[k], out)
        return out


@register_serde
@dataclass
class CycleSchedule(Schedule):
    """1cycle-style: warm up to max then anneal; simplified triangular cycle."""
    initial_value: float = 1e-4
    max_value: float = 1e-2
    cycle_length: int = 1000
    annealing_cycles: int = 0
    annealing_decay: float = 0.1

    def value(self, iteration, epoch=0):
        pos = (iteration % self.cycle_length) / max(self.cycle_length - 1, 1)
        tri = jnp.where(pos < 0.5, pos * 2, (1 - pos) * 2)
        return self.initial_value + (self.max_value - self.initial_value) * tri


@register_serde
@dataclass
class WarmupSchedule(Schedule):
    """Linear warmup into a wrapped schedule (transformer-era extension)."""
    warmup_iters: int = 100
    target: float = 1e-3

    def value(self, iteration, epoch=0):
        return self.target * jnp.clip(iteration / max(self.warmup_iters, 1), 0.0, 1.0)


def resolve(lr) -> Schedule:
    """Accept float or Schedule; return a Schedule."""
    if isinstance(lr, Schedule):
        return lr
    return FixedSchedule(float(lr))
