"""Dropout and weight-noise configurations.

Analogue of ``nn/conf/dropout/`` (Dropout, AlphaDropout, GaussianDropout,
GaussianNoise) and ``nn/conf/weightnoise/`` (DropConnect, WeightNoise).
All are pure functions of a PRNG key — train-time only, identity at inference,
matching reference semantics (``IDropout.applyDropout``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .distribution import Distribution


@dataclass
class IDropout:
    def apply(self, key, x, iteration=0):  # pragma: no cover - abstract
        raise NotImplementedError


@register_serde
@dataclass
class Dropout(IDropout):
    """Inverted dropout with retain probability p (reference Dropout.java)."""
    p: float = 0.5  # probability of *retaining* a unit, as in DL4J

    def apply(self, key, x, iteration=0):
        keep = jax.random.bernoulli(key, self.p, x.shape)
        return jnp.where(keep, x / self.p, 0.0)


@register_serde
@dataclass
class GaussianDropout(IDropout):
    rate: float = 0.5

    def apply(self, key, x, iteration=0):
        std = jnp.sqrt(self.rate / (1.0 - self.rate))
        return x * (1.0 + std * jax.random.normal(key, x.shape))


@register_serde
@dataclass
class GaussianNoise(IDropout):
    stddev: float = 0.1

    def apply(self, key, x, iteration=0):
        return x + self.stddev * jax.random.normal(key, x.shape)


@register_serde
@dataclass
class AlphaDropout(IDropout):
    """SELU-compatible dropout (reference AlphaDropout.java)."""
    p: float = 0.95
    alpha: float = -1.7580993408473766  # -alpha*lambda of SELU

    def apply(self, key, x, iteration=0):
        p = self.p
        a = (p + self.alpha ** 2 * p * (1 - p)) ** -0.5
        b = -a * (1 - p) * self.alpha
        keep = jax.random.bernoulli(key, p, x.shape)
        return a * jnp.where(keep, x, self.alpha) + b


def resolve(d) -> Optional[IDropout]:
    """Accept None, float retain-prob (DL4J style), or IDropout."""
    if d is None:
        return None
    if isinstance(d, IDropout):
        return d
    p = float(d)
    if p <= 0.0 or p >= 1.0:
        return None
    return Dropout(p)


# ---- weight noise (applied to params, not activations) ----------------------

@dataclass
class IWeightNoise:
    def apply(self, key, param, iteration=0):  # pragma: no cover - abstract
        raise NotImplementedError


@register_serde
@dataclass
class DropConnect(IWeightNoise):
    """Randomly zero weights during training (reference DropConnect.java)."""
    p: float = 0.5  # retain probability

    def apply(self, key, param, iteration=0):
        keep = jax.random.bernoulli(key, self.p, param.shape)
        return jnp.where(keep, param / self.p, 0.0)


@register_serde
@dataclass
class WeightNoise(IWeightNoise):
    """Additive or multiplicative noise from a distribution."""
    distribution: Optional[Distribution] = None
    additive: bool = True

    def apply(self, key, param, iteration=0):
        from .distribution import NormalDistribution
        dist = self.distribution or NormalDistribution(0.0, 0.01)
        noise = dist.sample(key, param.shape)
        return param + noise if self.additive else param * noise
