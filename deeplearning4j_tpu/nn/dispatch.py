"""Bounded asynchronous dispatch window for the fit loops (ISSUE 18).

JAX dispatch is asynchronous: a jitted step call returns device futures
immediately and the host is free to run step N+1's work (ETL wait,
ShapePolicy padding, h2d placement, listener/forensics bookkeeping)
while step N executes.  Left unbounded, that pipeline can run the host
arbitrarily far ahead of the device — deferred failures surface many
steps late, checkpoint saves capture a state the host believes exists
but the device hasn't produced, and runtime-queue memory grows with the
lead.  The whole-program-compilation argument (arxiv 1810.09868) says
keep work on-device and treat host round-trips as the tax; this module
bounds the tax's dual: how far the host may lead.

:class:`DispatchWindow` holds the loss tokens of in-flight steps.  Depth
semantics: at most ``depth`` steps are un-materialized at the moment a
new step is dispatched — :meth:`push` appends the fresh token then
blocks on the oldest until at most ``depth - 1`` remain, so ``depth=1``
reproduces the fully serial per-step-sync loop and the default
``depth=2`` overlaps one step of host work with device execution.

Contract-preserving drains (the fit loops own these):

- epoch ends and checkpoint-due boundaries call :meth:`drain` so
  exact-resume parity and the one-sync-per-epoch listener cadence hold;
- a monitor-armed fit already materializes per step (PR 10's same-step
  NaN contract), which empties the window as a side effect;
- exception paths call :meth:`abandon` — never block in a ``finally``.

Every drained token is NaN-checked host-side (``v != v``) with the
token's own iteration, so a deferred device failure at step N surfaces
within the window bound attributed to N, not to the step the host
happened to be dispatching.
"""
from __future__ import annotations

import os
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from ..observability.clock import monotonic_s

DEFAULT_DEPTH = 2
ENV_VAR = "DL4J_TPU_DISPATCH_DEPTH"


def configured_depth(default: int = DEFAULT_DEPTH) -> int:
    """The in-flight window depth: ``DL4J_TPU_DISPATCH_DEPTH`` (min 1),
    else ``default``.  Read per fit, not per process — tests and the
    pipeline bench flip it between runs."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return default
    try:
        depth = int(raw)
    except ValueError:
        return default
    return max(1, depth)


class DispatchWindow:
    """Bounded in-flight step window (see module docstring).

    owner: the network/model whose fit loop pushes here; drained tokens
    write ``owner.last_drained_score`` / ``owner.last_drained_iteration``
    so listeners can read steady-state rates at the drain boundary
    without forcing their own host sync.

    profiler: an armed :class:`~..observability.profiler.StepProfiler`
    (or None); each drained token calls ``profiler.drained(1)`` so the
    ``training_dispatch_depth`` gauge tracks real window occupancy.

    on_nan: callback ``(iteration, value)`` fired when a drained token
    materializes non-finite — the deferred-failure attribution hook.
    """

    __slots__ = ("depth", "owner", "profiler", "on_nan", "_window")

    def __init__(self, depth: Optional[int] = None, owner: Any = None,
                 profiler: Any = None,
                 on_nan: Optional[Callable[[int, float], None]] = None):
        self.depth = configured_depth() if depth is None \
            else max(1, int(depth))
        self.owner = owner
        self.profiler = profiler
        self.on_nan = on_nan
        self._window: deque = deque()

    def __len__(self) -> int:
        return len(self._window)

    def push(self, token: Any, iteration: int) -> None:
        """Admit one dispatched step's loss token; blocks on the oldest
        in-flight tokens until at most ``depth - 1`` remain (so the NEXT
        dispatch sees at most ``depth`` un-materialized steps)."""
        self._window.append((token, iteration))
        while len(self._window) > self.depth - 1:
            self._pop_block()

    def drain(self) -> None:
        """Materialize every in-flight token (epoch end, checkpoint-due
        boundary, explicit sync point)."""
        while self._window:
            self._pop_block()

    def drain_timed(self) -> List[Tuple[int, float]]:
        """Drain like :meth:`drain` but return ``(iteration,
        t_completed)`` per token — the profiler's pipeline-aware fence
        uses the completion spacing to attribute each drained step's
        device slice individually instead of billing the whole wait to
        the fenced step."""
        out = []
        while self._window:
            iteration = self._window[0][1]
            self._pop_block()
            out.append((iteration, monotonic_s()))
        return out

    def abandon(self) -> None:
        """Drop in-flight tokens WITHOUT blocking (exception paths: the
        loop's final un-guarded ``float(_score)`` still surfaces deferred
        failures through the param dependency chain)."""
        self._window.clear()

    def _pop_block(self) -> float:
        token, iteration = self._window.popleft()
        # float() alone is the sync: the loss is one output of the step's
        # single program, so its materialization implies the whole step
        # finished.  Deliberately NOT jax.block_until_ready — the stepprof
        # host-sync sweep counts those to pin the profiler's fence cadence,
        # and the window's bounded backpressure is loop-owned, not
        # profiler-owned.
        value = float(token)
        if self.owner is not None:
            self.owner.last_drained_score = value
            self.owner.last_drained_iteration = iteration
        if self.profiler is not None:
            self.profiler.drained(1)
        if value != value and self.on_nan is not None:
            self.on_nan(iteration, value)
        return value
