"""Parse-tree structure for recursive autoencoders (reference
``nn/layers/feedforward/autoencoder/recursive/Tree.java:32`` — legacy
recursive-AE support: labeled n-ary trees carrying per-node vectors,
predictions and reconstruction errors).

Kept as host-side plumbing: trees are irregular, data-dependent structures —
exactly what should NOT be traced under ``jit``.  The per-node ``vector`` /
``prediction`` payloads are arrays (device or numpy); batched tree math
belongs to whatever model consumes the traversal (e.g. pad-and-mask over
``get_leaves()`` order).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

__all__ = ["Tree"]


class Tree:
    """N-ary labeled tree node.  Mirrors the reference surface: tokens,
    type/value/label/goldLabel, vector/prediction payloads, children/parent
    links, error accumulation (``error``/``errorSum``), traversal helpers
    (``is_leaf``, ``is_pre_terminal``, ``depth``, ``ancestor``,
    ``get_leaves``, ``yield_words``), and deep ``clone``."""

    def __init__(self, tokens: Optional[Sequence[str]] = None,
                 parent: Optional["Tree"] = None):
        self.tokens: List[str] = list(tokens or [])
        self.parent: Optional[Tree] = parent
        self.children: List[Tree] = []
        self.type: Optional[str] = None
        self.value: Optional[str] = None
        self.label: Optional[str] = None
        self.gold_label: int = 0
        self.tags: List[str] = []
        self.vector: Any = None        # per-node embedding (Tree.java:360)
        self.prediction: Any = None    # per-node softmax (Tree.java:368)
        self.error: float = 0.0
        self.head_word: Optional[str] = None

    # ---------------------------------------------------------- structure --
    def is_leaf(self) -> bool:
        return not self.children

    def is_pre_terminal(self) -> bool:
        """Exactly one child, and that child is a leaf (Tree.java:162)."""
        return len(self.children) == 1 and self.children[0].is_leaf()

    def first_child(self) -> Optional["Tree"]:
        return self.children[0] if self.children else None

    def last_child(self) -> Optional["Tree"]:
        return self.children[-1] if self.children else None

    def depth(self) -> int:
        """Height below this node: 0 for a leaf (Tree.java:189)."""
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def depth_of(self, node: "Tree") -> int:
        """Depth of ``node`` below this subtree, -1 if absent
        (Tree.java:210)."""
        if node is self:
            return 0
        for c in self.children:
            d = c.depth_of(node)
            if d >= 0:
                return d + 1
        return -1

    def ancestor(self, height: int, root: "Tree") -> Optional["Tree"]:
        """Ancestor ``height`` levels up, found via ``root``
        (Tree.java:258)."""
        node: Optional[Tree] = self
        for _ in range(height):
            node = node.parent_in(root) if node is not None else None
        return node

    def parent_in(self, root: "Tree") -> Optional["Tree"]:
        """Locate this node's parent by searching from ``root``
        (Tree.java:231 — the reference recomputes parents from the root
        rather than trusting the link)."""
        for c in root.children:
            if c is self:
                return root
            found = self.parent_in(c)
            if found is not None:
                return found
        return None

    # ------------------------------------------------------------- content --
    def yield_words(self) -> List[str]:
        """Leaf tokens, left to right (Tree.java:94 ``yield()``)."""
        if self.is_leaf():
            return list(self.tokens) if self.tokens else (
                [self.value] if self.value is not None else [])
        out: List[str] = []
        for c in self.children:
            out.extend(c.yield_words())
        return out

    def get_leaves(self) -> List["Tree"]:
        """All leaf nodes, left to right (Tree.java:300)."""
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.get_leaves())
        return out

    def error_sum(self) -> float:
        """This node's error plus all descendants' (Tree.java:278)."""
        return self.error + sum(c.error_sum() for c in self.children)

    def clone(self) -> "Tree":
        """Deep structural copy; payload arrays are shared (they are
        immutable under JAX), host fields copied (Tree.java:325)."""
        t = Tree(self.tokens)
        t.type, t.value, t.label = self.type, self.value, self.label
        t.gold_label, t.tags = self.gold_label, list(self.tags)
        t.vector, t.prediction = self.vector, self.prediction
        t.error, t.head_word = self.error, self.head_word
        for c in self.children:
            cc = c.clone()
            cc.parent = t
            t.children.append(cc)
        return t

    def connect(self, children: Sequence["Tree"]) -> None:
        """Attach children, fixing parent links (Tree.java ``connect``)."""
        self.children = list(children)
        for c in self.children:
            c.parent = self

    def __repr__(self):
        kind = "leaf" if self.is_leaf() else f"{len(self.children)} children"
        return f"Tree({self.label or self.value or self.tokens}, {kind})"
