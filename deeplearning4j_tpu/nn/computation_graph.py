"""ComputationGraph — DAG network runtime.

TPU-native re-design of ``nn/graph/ComputationGraph.java:87``: the reference
walks the topological order per call, managing workspaces and hand-accumulated
fan-in epsilons; here the whole DAG (forward + loss + backward + update) is
traced once into a single jitted XLA program.  Fan-in gradient accumulation is
what jax.grad does by construction; workspace reuse is XLA's buffer allocator
plus argument donation.

Multi-input / multi-output: ``fit`` takes a MultiDataSet-shaped batch
(features list, labels list, optional masks); the loss is the sum over output
layers (reference computeGradientAndScore, ComputationGraph.java:1310-1320).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import precision as _precision
from ._common import (_cast_floats, apply_constraints_all,
                      apply_gradient_norm_all, build_tx,
                      fit_on_device_epochs, hyperparam_conf)
from .compile_cache import shared_jit, topology_signature
from .multilayer import _cast_act
from .conf.computation_graph import (ComputationGraphConfiguration,
                                     GraphVertexConf, LayerVertex)
from .conf.updaters import Sgd, UpdaterConf
from .layers.base import BaseLayerConf
from ..data.shapes import default_shape_policy
from ..observability.clock import monotonic_s
from ..train.listeners import TrainingListener

Array = jax.Array


def _as_list(x) -> List:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _vertex_confs(conf) -> Dict[str, Any]:
    return {name: getattr(v, "layer", None)
            for name, v in conf.vertices.items()}


def _graph_forward(conf, params, state, inputs: List[Array], *, train: bool,
                   key, masks: Optional[List[Optional[Array]]] = None,
                   exclude_outputs: bool = False, precision=None):
    """Walk the static topological order; returns (acts, new_state, masks).

    acts: dict vertex-name -> activation (plus network inputs).  A free
    function over the configuration — never touches a graph instance — so
    the jitted programs built from it live in the process-global trace
    cache and serve every equal-topology graph (clones, master replicas).
    """
    acts: Dict[str, Array] = {}
    mask_of: Dict[str, Optional[Array]] = {}
    for i, n in enumerate(conf.network_inputs):
        acts[n] = inputs[i]
        mask_of[n] = masks[i] if masks else None
    new_state = dict(state)
    # output vertices whose activation nothing consumes can be skipped
    # when the caller only needs pre-output activations for the loss
    consumed = {src for ins in conf.vertex_inputs.values() for src in ins}
    for vi, name in enumerate(conf.topological_order):
        v = conf.vertices[name]
        if exclude_outputs and name in conf.network_outputs and \
                name not in consumed and isinstance(v, LayerVertex) and \
                hasattr(v.layer, "compute_loss"):
            continue
        ins = conf.vertex_inputs[name]
        xs = [acts[s] for s in ins]
        ms = [mask_of.get(s) for s in ins]
        # LastTimeStepVertex keys sequence length off a *named* input mask
        mi = getattr(v, "mask_input", None)
        if mi:
            ms = [mask_of.get(mi)] + ms[1:]
        lkey = jax.random.fold_in(key, vi) if key is not None else None
        if precision is not None:
            vdt = precision.layer_dtype(getattr(v, "layer", None) or v)
            xs = [_cast_act(x, vdt) for x in xs]
        variables = {"params": params.get(name, {}),
                     "state": state.get(name, {})}
        if train and conf.defaults.get("cache_mode") == "remat" and \
                isinstance(v, LayerVertex):
            # rematerialize per-vertex activations on the backward pass
            # (the WorkspaceMode/CacheMode role: trade FLOPs for HBM —
            # SURVEY §7 "Workspaces → jax.checkpoint")
            def _apply(vv, xx, kk, mm, _v=v):
                return _v.apply(vv, xx, train=True, key=kk, masks=mm)
            y, lstate = jax.checkpoint(_apply)(variables, xs, lkey, ms)
        else:
            y, lstate = v.apply(variables, xs, train=train, key=lkey,
                                masks=ms)
        acts[name] = y
        new_state[name] = lstate
        mask_of[name] = v.feed_forward_mask(ms, xs)
    return acts, new_state, mask_of


def _graph_loss(conf, params, state, inputs, labels, *, train: bool, key,
                masks=None, label_masks=None, precision=None):
    acts, new_state, mask_of = _graph_forward(
        conf, params, state, inputs, train=train, key=key, masks=masks,
        exclude_outputs=True, precision=precision)
    # accumulate in the loss dtype (a dtype-defaulted zeros(()) start is
    # f64 under x64 and would promote every head's loss — graftaudit AX001)
    total = None
    for oi, name in enumerate(conf.network_outputs):
        v = conf.vertices[name]
        if not (isinstance(v, LayerVertex) and
                hasattr(v.layer, "compute_loss")):
            raise ValueError(
                f"network output '{name}' is not an output layer vertex")
        src = conf.vertex_inputs[name][0]
        h = acts[src]
        if precision is not None:
            # head matmul in the compute dtype; the loss reductions
            # upcast to f32 inside nn/losses
            h = _cast_act(h, precision.layer_dtype(v.layer))
        lm = None
        if label_masks is not None and oi < len(label_masks):
            lm = label_masks[oi]
        if lm is None:
            lm = mask_of.get(src)
        lkey = (jax.random.fold_in(key, 10_000 + oi)
                if key is not None else None)
        variables = {"params": params.get(name, {}),
                     "state": state.get(name, {})}
        l = v.compute_loss(variables, h, labels[oi], train=train,
                           key=lkey, mask=lm)
        total = l if total is None else total + l
    if total is None:
        total = jnp.zeros((), jnp.float32)
    reg = jnp.zeros((), dtype=total.dtype)
    for name, v in conf.vertices.items():
        lp = params.get(name, {})
        if lp:
            reg = reg + v.regularization_score(lp)
        if getattr(getattr(v, "layer", None), "AUX_LOSS", False):
            aux = new_state.get(name, {}).get("aux_loss")
            if aux is not None:
                reg = reg + aux
    return total + reg, new_state


def _build_graph_fn(conf, tx, kind: str):
    """Build the Python function behind one jitted graph entry point;
    returns ``(fun, donate_argnums)``.  Closures capture only conf/tx
    (shared-cache safe; the per-instance closure is the JX013 hazard)."""
    outs = conf.network_outputs
    if kind == "output":
        def fn(params, state, xs):
            acts, _, _ = _graph_forward(conf, params, state, xs,
                                        train=False, key=None)
            return [acts[o] for o in outs]
        return fn, ()
    if kind == "output_train":
        def fn(params, state, xs, key):
            acts, _, _ = _graph_forward(conf, params, state, xs,
                                        train=True, key=key)
            return [acts[o] for o in outs]
        return fn, ()
    if kind == "score":
        def fn(params, state, xs, ys, label_masks):
            return _graph_loss(conf, params, state, xs, ys, train=False,
                               key=None, label_masks=label_masks)
        return fn, ()
    if kind == "train_step":
        # maximal donation (graftaudit AX007): the fused-RNG step returns
        # the successor key as an alias-matched output, so the key buffer
        # donates and recycles in place with the training carry
        return _build_graph_train_step(conf, tx), (0, 1, 2, 3)
    raise KeyError(kind)


def _build_graph_train_step(conf, tx):
    gn_mode = conf.defaults.get("gradient_normalization")
    gn_thr = float(conf.defaults.get(
        "gradient_normalization_threshold", 1.0))
    pol = _precision.resolve(conf.defaults)
    confs = _vertex_confs(conf)
    for name, lc in confs.items():
        if getattr(lc, "sparse_grad", False) or \
                getattr(getattr(lc, "layer", None), "sparse_grad", False):
            # surfaced at build time, never a silent dense fallback: the
            # densified pre-pass (nn/sparse) is wired into the
            # MultiLayerNetwork train step only — a graph vertex here
            # would quietly train with the dense [vocab, dim] cotangent
            # the flag promises to eliminate
            raise ValueError(
                f"vertex '{name}': sparse_grad=True is supported on "
                "MultiLayerNetwork (first-layer embedding) only; the "
                "ComputationGraph train step has no densified sparse-"
                "gradient pre-pass — drop the flag, or move the "
                "embedding model to a MultiLayerNetwork stack")
    cast_map = {}
    if pol is not None:
        for name, v in conf.vertices.items():
            dt = pol.layer_dtype(getattr(v, "layer", None) or v)
            if dt not in (None, "float32"):
                cast_map[name] = dt

    def step(params, state, opt_state, key, xs, ys, masks, label_masks):
        # fused RNG succession (see nn/multilayer._build_train_step): the
        # host-side split moves into the program — bit-identical key
        # sequence, one less dispatch, and the key becomes donatable
        new_rng, key = jax.random.split(key)
        if pol is not None:
            xs = [_cast_act(x, pol.compute_dtype) for x in xs]
        ls = state.get(_precision.SCALE_STATE_KEY) \
            if pol is not None and pol.scaled else None
        scale = ls["scale"] if ls is not None else None

        def loss_fn(p):
            if cast_map:
                p = {k: (_cast_floats(v, cast_map[k]) if k in cast_map
                         else v) for k, v in p.items()}
            loss, new_state = _graph_loss(conf, p, state, xs, ys,
                                          train=True, key=key, masks=masks,
                                          label_masks=label_masks,
                                          precision=pol)
            obj = loss * scale if scale is not None else loss
            return obj, (loss, new_state)
        (_obj, (loss, new_state)), grads = \
            jax.value_and_grad(loss_fn, has_aux=True)(params)
        finite = None
        if scale is not None:
            grads, finite = _precision.unscale_and_check(grads, scale)
        grads = apply_gradient_norm_all(grads, confs, gn_mode, gn_thr)
        gleaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in gleaves)) \
            if gleaves else jnp.zeros((), jnp.float32)
        glayer = {k: jnp.sqrt(sum(jnp.sum(g * g)
                                  for g in jax.tree_util.tree_leaves(v)))
                  for k, v in grads.items() if v}
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = apply_constraints_all(new_params, confs)
        if pol is not None:
            new_state = _cast_floats(new_state, jnp.float32,
                                     only=pol.compute_dtype)
        gstats = {"global_norm": gnorm, "layer_norms": glayer}
        if ls is not None:
            # overflow: skip the step wholesale (nn/precision)
            new_params, new_opt, new_state, _sel = \
                _precision.overflow_skip(
                    pol, ls, finite, params, new_params, opt_state,
                    new_opt, state, new_state, gstats)
        return new_params, new_state, new_opt, new_rng, loss, gstats

    return step


class ComputationGraph:
    """DAG network: init → fit/output/score/evaluate."""

    def __init__(self, conf: ComputationGraphConfiguration):
        conf.resolve()
        self.conf = conf
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.last_batch_size = 0
        self.listeners: List[TrainingListener] = []
        self._score = float("nan")
        # drain-boundary telemetry (nn/dispatch.DispatchWindow): see
        # MultiLayerNetwork.__init__
        self.last_drained_score = float("nan")
        self.last_drained_iteration = -1
        self._last_grad_stats = None
        self._last_step_traced = False
        # per-fit StepProfiler (see MultiLayerNetwork): _fit_one credits
        # its h2d/listener slices through it when a fit attaches one
        self._stepprof = None
        self._tx = None
        self._rng = jax.random.PRNGKey(conf.seed)
        # instance view over the process-global trace cache (compile_cache)
        self._jit_cache: Dict[Any, Any] = {}
        self._topo_sig: Optional[str] = None
        self._pad_safe: Optional[bool] = None
        self.shape_policy = default_shape_policy()

    # ------------------------------------------------------------------ init
    def init(self) -> "ComputationGraph":
        key = jax.random.PRNGKey(self.conf.seed)
        self.params, self.state = {}, {}
        for name in self.conf.topological_order:
            v = self.conf.vertices[name]
            key, sub = jax.random.split(key)
            itypes = self.conf.vertex_input_types.get(name, [None])
            out = v.init(sub, itypes)
            self.params[name] = out.get("params", {})
            self.state[name] = out.get("state", {})
        ls = _precision.init_scale_state(
            _precision.resolve(self.conf.defaults))
        if ls is not None:
            self.state[_precision.SCALE_STATE_KEY] = ls
        self._tx = self._build_tx()
        self.opt_state = self._tx.init(self.params)
        return self

    def _default_updater(self) -> UpdaterConf:
        u = self.conf.defaults.get("updater")
        return u if u is not None else Sgd(learning_rate=0.1)

    def _layer_conf_map(self):
        return {name: getattr(v, "layer", None)
                for name, v in self.conf.vertices.items()}

    def _build_tx(self) -> optax.GradientTransformation:
        return build_tx(self._default_updater(), self._layer_conf_map(),
                        self.params)

    # -------------------------------------------------------------- forward
    def _forward(self, params, state, inputs: List[Array], *, train: bool,
                 key, masks: Optional[List[Optional[Array]]] = None,
                 exclude_outputs: bool = False):
        """Delegate to the conf-parameterized ``_graph_forward`` (kept as a
        method for external callers)."""
        return _graph_forward(self.conf, params, state, inputs, train=train,
                              key=key, masks=masks,
                              exclude_outputs=exclude_outputs)

    def _loss(self, params, state, inputs, labels, *, train: bool, key,
              masks=None, label_masks=None):
        """Delegate to the conf-parameterized ``_graph_loss``."""
        return _graph_loss(self.conf, params, state, inputs, labels,
                           train=train, key=key, masks=masks,
                           label_masks=label_masks)

    # ---------------------------------------------------------- public API
    def output(self, *inputs, train: bool = False):
        """Activations of the network outputs (reference ``output(...)``).
        Returns a single array if one output, else a list.  Ragged eval
        batches pad onto a compiled bucket and the padded rows are sliced
        off every head (row-wise inference is value-preserving)."""
        xs = [jnp.asarray(x) for x in inputs]
        n = -1
        pol = self.shape_policy
        if not train and pol is not None and pol.enabled and xs and \
                all(getattr(x, "ndim", 1) >= 2 for x in xs) and \
                self._pad_output_safe():
            padded, b = pol.pad_eval_rows_multi(xs)
            if padded is not xs:   # same list object back == nothing padded
                xs, n = padded, b
        if train:
            self._rng, key = jax.random.split(self._rng)
            fn = self._get_jitted("output_train")
            ys = fn(self.params, self.state, xs, key)
        else:
            fn = self._get_jitted("output")
            ys = fn(self.params, self.state, xs)
        if n >= 0:
            ys = [y[:n] if getattr(y, "shape", (0,))[0] > n else y
                  for y in ys]
        return ys[0] if len(ys) == 1 else list(ys)

    def output_single(self, *inputs, train: bool = False) -> Array:
        y = self.output(*inputs, train=train)
        if isinstance(y, list):
            raise ValueError("output_single on a multi-output graph")
        return y

    def feed_forward(self, *inputs, train: bool = False) -> Dict[str, Array]:
        """All vertex activations keyed by vertex name."""
        xs = [jnp.asarray(x) for x in inputs]
        key = None
        if train:
            self._rng, key = jax.random.split(self._rng)
        acts, _, _ = self._forward(self.params, self.state, xs, train=train,
                                   key=key)
        return acts

    def score(self, dataset=None, inputs=None, labels=None) -> float:
        """Loss on a dataset; with no arguments, the score of the most
        recent training minibatch (reference ``score()`` / ``score(DataSet)``
        — same contract as MultiLayerNetwork)."""
        if dataset is None and inputs is None:
            return float(self._score)   # device scalar mid-fit_on_device
        if dataset is not None:
            inputs, labels, _, _ = self._normalize_batch(dataset)
        inputs = [jnp.asarray(x) for x in _as_list(inputs)]
        labels = [jnp.asarray(y) for y in _as_list(labels)]
        lms = None
        pol = self.shape_policy
        if pol is not None and pol.enabled and self._pad_eval_safe():
            # ragged scoring batch rides a compiled bucket; padded rows
            # are masked out of every output's loss
            inputs, labels, lms = pol.pad_multi_batch(inputs, labels, None,
                                                      path="score")
        fn = self._get_jitted("score")
        loss, _ = fn(self.params, self.state, inputs, labels, lms)
        return float(loss)

    def _topology_sig(self) -> str:
        if self._topo_sig is None:
            self._topo_sig = topology_signature(self.conf)
        return self._topo_sig

    def invalidate_compile_cache(self) -> "ComputationGraph":
        """Drop compiled-function views after IN-PLACE conf edits (see
        ``MultiLayerNetwork.invalidate_compile_cache``)."""
        self._jit_cache = {}
        self._topo_sig = None
        self._pad_safe = None
        return self

    def _get_jitted(self, kind: str):
        fn = self._jit_cache.get(kind)
        if fn is None:
            if self._tx is None and kind == "train_step":
                self._tx = self._build_tx()
            fn = shared_jit(
                (type(self).__name__, self._topology_sig(), kind),
                lambda: _build_graph_fn(self.conf, self._tx, kind),
                name=kind)
            self._jit_cache[kind] = fn
        return fn

    def _pad_flags(self):
        """See ``MultiLayerNetwork._pad_flags``: (row-independent
        inference, loss-path eval safe, train safe)."""
        if self._pad_safe is None:
            from .layers.normalization import BatchNormalization
            row_indep = eval_safe = train_safe = True
            for name, v in self.conf.vertices.items():
                lc = getattr(v, "layer", None)
                if getattr(lc, "AUX_LOSS", False):
                    # MoE: padded rows compete for expert capacity AND the
                    # whole-batch aux term defeats the label mask
                    row_indep = False
                if name in self.conf.network_outputs and lc is not None \
                        and not getattr(lc, "SUPPORTS_LOSS_MASK", True):
                    eval_safe = False
                if isinstance(hyperparam_conf(lc) or lc,
                              BatchNormalization):
                    train_safe = False
            eval_safe = eval_safe and row_indep
            train_safe = train_safe and eval_safe
            self._pad_safe = (row_indep, eval_safe, train_safe)
        return self._pad_safe

    def _pad_output_safe(self) -> bool:
        return self._pad_flags()[0]

    def _pad_eval_safe(self) -> bool:
        return self._pad_flags()[1]

    def _pad_train_safe(self) -> bool:
        return self._pad_flags()[2]

    def _fit_one(self, xs, ys, ms, lms):
        """One train step (shared by fit's inner loop and fit_batch).
        Leaves ``_score`` as the ASYNC device loss scalar — see
        ``MultiLayerNetwork._fit_one`` (the host-sync sweep); the fit
        loop materializes once at the end, ``fit_batch`` on return."""
        prof = self._stepprof
        if prof is not None:
            _t = monotonic_s()
        xs = [jnp.asarray(x) for x in xs]
        ys = [jnp.asarray(y) for y in ys]
        ms = None if ms is None else [
            None if m is None else jnp.asarray(m) for m in _as_list(ms)]
        lms = None if lms is None else [
            None if m is None else jnp.asarray(m) for m in _as_list(lms)]
        if prof is not None:
            prof.mark("h2d", monotonic_s() - _t)
        self.last_batch_size = int(xs[0].shape[0])
        pol = self.shape_policy
        if pol is not None and pol.enabled and ms is None and \
                self._pad_train_safe():
            # ragged batches pad onto an already-compiled bucket; padded
            # rows carry a zero label mask on EVERY output head
            xs, ys, lms = pol.pad_multi_batch(xs, ys, lms, path="train")
        step_fn = self._get_jitted("train_step")
        # fused-RNG step: splits the key inside the program (bit-identical
        # to the host split it replaces) and returns the successor
        (self.params, self.state, self.opt_state, self._rng, loss,
         gstats) = step_fn(
            self.params, self.state, self.opt_state, self._rng, xs, ys,
            ms, lms)
        self._score = loss
        self._last_grad_stats = gstats
        self._last_step_traced = bool(getattr(step_fn, "last_call_traced",
                                              False))
        self.iteration += 1
        if prof is None:
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, self.epoch)
        else:
            _t = monotonic_s()
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, self.epoch)
            prof.mark("listener", monotonic_s() - _t)
        return self._score

    def fit_batch(self, batch) -> float:
        """One train step on one batch WITHOUT epoch bookkeeping (used by
        EarlyStoppingTrainer, which owns the epoch loop)."""
        if self.params == {}:
            self.init()
        return float(self._fit_one(*self._normalize_batch(batch)))

    def fit(self, data=None, labels=None, *, epochs: int = 1,
            masks=None, label_masks=None, checkpoint=None,
            resume_from=None) -> "ComputationGraph":
        """Train.  ``data`` may be (inputs, labels) (each an array or list of
        arrays) or an iterable of MultiDataSet-shaped batches.

        ``checkpoint``/``resume_from``: crash-consistent periodic saves and
        exact mid-epoch resume (``faulttolerance.CheckpointConfig``; see
        ``MultiLayerNetwork.fit``)."""
        if self.params == {}:
            self.init()
        if labels is not None:
            one = (_as_list(data), _as_list(labels), masks, label_masks)
            batches_factory = lambda: [one]
        elif isinstance(data, tuple) and len(data) in (2, 4):
            # fit((inputs, labels)) single-batch form — a tuple is NOT an
            # iterator of batches
            batches_factory = lambda: [self._normalize_batch(data)]
        elif hasattr(data, "features"):
            # a single DataSet/MultiDataSet IS one batch, not a batch iterator
            batches_factory = lambda: [self._normalize_batch(data)]
        elif hasattr(data, "reset") or hasattr(data, "__iter__"):
            if not hasattr(data, "reset") and epochs > 1 and iter(data) is data:
                data = [self._normalize_batch(b) for b in data]
                batches_factory = lambda: data
            else:
                src = data

                def batches_factory():
                    if hasattr(src, "reset"):
                        src.reset()
                    for b in src:
                        yield self._normalize_batch(b)
        else:
            raise ValueError("fit() needs (inputs, labels) or an iterator")

        # constructed only after every validation raise above: the SIGTERM
        # hook it installs must always reach the loop's finally/close()
        ckpt = None
        if checkpoint is not None or resume_from is not None:
            from ..faulttolerance.checkpoint import FitCheckpointer
            ckpt = FitCheckpointer(self, checkpoint, resume_from)
        from ..observability.health import get_health_monitor
        from ..observability.profiler import step_profiler_for
        from ..observability.recorder import get_flight_recorder
        from .multilayer import _StepForensics
        rec = get_flight_recorder()
        rec_on = rec is not None and rec.enabled
        mon = get_health_monitor()
        forensics = _StepForensics(self, rec, mon, ckpt) \
            if (rec_on or mon is not None) else None
        # per-step phase attribution with a sampled device fence (see
        # MultiLayerNetwork.fit / observability/profiler.py)
        prof = step_profiler_for("train_step")
        self._stepprof = prof

        # bounded async dispatch (ISSUE 18; see MultiLayerNetwork.fit):
        # up to DL4J_TPU_DISPATCH_DEPTH steps in flight, drained at epoch
        # ends and checkpoint boundaries, NaN-checked per drained token
        from .dispatch import DispatchWindow

        def _nan_at_drain(iteration, value):
            if rec_on:
                rec.record("train", "nan_at_drain", score=value,
                           iteration=int(iteration))
        win = DispatchWindow(owner=self, profiler=prof,
                             on_nan=_nan_at_drain)
        start_epoch = ckpt.start_epoch if ckpt is not None else 0
        stop = False
        try:
            for ep in range(start_epoch, epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self)
                # resume cursor: skip already-consumed batches of the first
                # resumed epoch without fitting (see MultiLayerNetwork.fit)
                skip = ckpt.skip_batches \
                    if (ckpt is not None and ep == ckpt.start_epoch) else 0
                seq = 0
                for batch in batches_factory():
                    if seq < skip:
                        seq += 1
                        continue
                    t_step = monotonic_s()
                    if prof is not None:
                        prof.begin(t_step)
                    self._fit_one(*batch)
                    if prof is not None:
                        prof.dispatched(self._score, window=win)
                    seq += 1
                    t_end = monotonic_s()
                    if forensics is not None and forensics.step(
                            ep, seq, self._last_step_traced,
                            t_end - t_step, t_end):
                        stop = True   # opt-in health stop: clean return
                    if prof is not None:
                        prof.lap("forensics")
                    if not stop and ckpt is not None:
                        if ckpt.due():
                            # checkpoint boundary drains the window first
                            # (mid-window resume stays digest-exact)
                            win.drain()
                        if ckpt.after_batch(ep, seq):
                            stop = True   # SIGTERM: final save taken
                    if prof is not None:
                        if ckpt is not None:
                            prof.lap("checkpoint")
                        prof.end(self.iteration, self._last_step_traced)
                    if stop:
                        break
                    # admit this step into the in-flight window (bounded-
                    # pipeline backpressure point)
                    win.push(self._score, self.iteration)
                if stop:
                    break
                # ONE materialization per epoch (fit_on_device's sync
                # convention): steps pipelined async all epoch; epoch-end
                # listeners (MetricsListener score/grad-norm) see a host
                # float without forcing their own sync
                win.drain()
                self._score = float(self._score)
                if prof is not None:
                    prof.materialized()
                for lst in self.listeners:
                    lst.on_epoch_end(self)
                self.epoch += 1
                if ckpt is not None and ckpt.after_epoch(ep):
                    stop = True
                    break
            # stop-path exits break before the epoch-end drain
            win.drain()
        except Exception as e:
            # never block on in-flight work while unwinding (the final
            # un-guarded float(_score) still surfaces deferred failures)
            win.abandon()
            if rec_on:   # crash forensics before the exception propagates
                if forensics is not None:
                    try:
                        forensics.flush()
                    except Exception:
                        pass   # forensics must not mask the real error
                rec.record("train", "fit_exception",
                           error=f"{type(e).__name__}: {e}",
                           iteration=int(self.iteration))
                rec.maybe_dump(
                    "fit_exception",
                    directory=(ckpt.manager.directory
                               if ckpt is not None and ckpt.manager
                               is not None else None))
            raise
        finally:
            if forensics is not None:
                try:
                    forensics.flush()
                except Exception:
                    pass
            if prof is not None:
                self._stepprof = None
                try:
                    prof.flush()
                except Exception:
                    pass   # profile telemetry must not mask the real error
            if ckpt is not None:
                ckpt.close()
        # ONE materialization for the whole fit (async steps pipeline).
        # NOT exception-guarded: deferred device failures surface here
        self._score = float(self._score)
        return self

    def fit_on_device(self, inputs, labels, *, batch_size: int,
                      epochs: int = 1, shuffle: bool = True,
                      checkpoint=None, resume_from=None
                      ) -> "ComputationGraph":
        """Device-resident epoch training for graphs: the dataset stays in
        HBM and one jitted program scans the train step over all minibatches
        (one dispatch per epoch; see ``MultiLayerNetwork.fit_on_device``).
        ``inputs``/``labels``: array or list of arrays (multi-input/output).
        """
        if self.params == {}:
            self.init()
        ckpt = None
        if checkpoint is not None or resume_from is not None:
            from ..faulttolerance.checkpoint import FitCheckpointer
            ckpt = FitCheckpointer(self, checkpoint, resume_from)
        step = self._get_jitted("train_step")
        return fit_on_device_epochs(
            self, [jnp.asarray(a) for a in _as_list(inputs)],
            [jnp.asarray(a) for a in _as_list(labels)], batch_size, epochs,
            shuffle,
            call_step=lambda p, s, o, k, bx, by: step(p, s, o, k, bx, by,
                                                      None, None),
            fit_tail=lambda xt, yt: self._fit_one(xt, yt, None, None),
            ckpt=ckpt)

    @staticmethod
    def _normalize_batch(b):
        if isinstance(b, (tuple, list)):
            if len(b) == 2:
                return _as_list(b[0]), _as_list(b[1]), None, None
            if len(b) == 4:
                return (_as_list(b[0]), _as_list(b[1]),
                        None if b[2] is None else _as_list(b[2]),
                        None if b[3] is None else _as_list(b[3]))
        if hasattr(b, "features"):
            fm = getattr(b, "features_mask", None)
            lm = getattr(b, "labels_mask", None)
            return (_as_list(b.features), _as_list(b.labels),
                    None if fm is None else _as_list(fm),
                    None if lm is None else _as_list(lm))
        raise ValueError(f"cannot interpret batch of type {type(b)}")

    # ------------------------------------------------------------- queries
    def get_score(self) -> float:
        # may be a device scalar mid-fit_on_device (kept async so epochs
        # pipeline); materialize on demand
        return float(self._score)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def param_bytes(self, per_device: bool = False) -> int:
        """Parameter memory: global bytes, or with ``per_device=True`` the
        bytes ONE device holds — a ZeRO-3 sharded graph (``parallel/
        sharded.py`` NamedSharding layout) reports ~1/dp of global."""
        from ..parallel.sharded import param_bytes, per_device_param_bytes
        return per_device_param_bytes(self.params) if per_device \
            else param_bytes(self.params)

    def evaluate(self, iterator_or_x, y=None):
        from ..evaluation.classification import Evaluation
        return self._evaluate_with(Evaluation(), iterator_or_x, y)

    def evaluate_regression(self, iterator_or_x, y=None):
        from ..evaluation.regression import RegressionEvaluation
        return self._evaluate_with(RegressionEvaluation(), iterator_or_x, y)

    def evaluate_roc(self, iterator_or_x, y=None, threshold_steps: int = 0):
        from ..evaluation.roc import ROC
        return self._evaluate_with(ROC(threshold_steps), iterator_or_x, y)

    def _evaluate_with(self, ev, iterator_or_x, y=None):
        """First network output vs labels (reference ComputationGraph
        evaluate/evaluateROC/evaluateRegression)."""
        for xs, yy in self._eval_batches(iterator_or_x, y):
            out = self.output(*xs)
            if isinstance(out, list):
                out = out[0]
            ev.eval(np.asarray(yy), np.asarray(out))
        return ev

    def _eval_batches(self, it, y):
        if y is not None:
            yield _as_list(it), _as_list(y)[0]
            return
        if hasattr(it, "reset"):
            it.reset()
        for b in it:
            xs, ys, _, _ = self._normalize_batch(b)
            yield xs, ys[0]

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def clone(self) -> "ComputationGraph":
        import copy
        other = ComputationGraph(copy.deepcopy(self.conf))
        copy_tree = lambda t: jax.tree_util.tree_map(lambda a: jnp.array(a), t)
        other.params = copy_tree(self.params)
        other.state = copy_tree(self.state)
        other._tx = other._build_tx()
        if self.opt_state is not None:
            other.opt_state = copy_tree(self.opt_state)
        else:
            other.init()
        # split the parent stream per clone (identical dropout masks across
        # data-parallel replicas would correlate their gradient noise);
        # the deepcopied conf signs identically, so compiled steps are
        # reused from the shared trace cache
        self._rng, other._rng = jax.random.split(self._rng)
        other.shape_policy = self.shape_policy
        other.iteration = self.iteration
        other.epoch = self.epoch
        return other


def check_graph_gradients(net: ComputationGraph, inputs, labels, *,
                          epsilon: float = 1e-6, max_rel_error: float = 1e-3,
                          min_abs_error: float = 1e-8, masks=None,
                          label_masks=None, print_results: bool = False,
                          subset: Optional[int] = None, seed: int = 12345,
                          exclude: tuple = ("centers",)) -> bool:
    """GradientCheckUtil for graphs (reference checkGradients CG variant)."""
    from ..utils.gradient_check import _check_gradients_impl
    if not net.params:
        net.init()
    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float64), net.params)
    state = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float64)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, net.state)
    xs = [jnp.asarray(x, jnp.float64) for x in _as_list(inputs)]
    ys = [jnp.asarray(y, jnp.float64) for y in _as_list(labels)]

    @jax.jit  # graftlint: disable=JX028  (f64 gradient-check probe; cold diagnostic path, never steady-state)
    def loss_fn(p):
        loss, _ = net._loss(p, state, xs, ys, train=False, key=None,
                            masks=masks, label_masks=label_masks)
        return loss

    analytic = jax.grad(loss_fn)(params)
    return _check_gradients_impl(loss_fn, params, analytic, epsilon,
                                 max_rel_error, min_abs_error, print_results,
                                 subset, seed, exclude)
