"""MultiLayerNetwork — sequential-stack network runtime.

TPU-native re-design of ``nn/multilayer/MultiLayerNetwork.java:90``.  Where the
reference drives per-layer Java loops (``feedForwardToLayer`` :903,
``calcBackpropGradients`` :1282) with params as views into one flat array, the
TPU design traces the whole forward+backward+update into ONE jitted XLA
program:

  - forward:   python loop over layer confs, unrolled at trace time (static)
  - backward:  ``jax.value_and_grad`` over the whole stack (replaces the
               hand-written backpropGradient chain)
  - update:    optax transforms fused into the same program; buffer donation
               gives in-place semantics (the flat param view's job)
  - gradient normalization (``BaseMultiLayerUpdater.preApply`` :318) and
    constraints run inside the same program.

Param pytree layout: ``{"layer_0": {...}, "layer_1": {...}}`` keyed by position,
so checkpoints are stable under layer renames (the reference's flat
``coefficients.bin`` role is played by the serialized pytree; see
utils/model_serializer.py).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import precision as _precision
from . import scan_layers as _scan_layers
from . import sparse as _sparse
from ._common import (_cast_floats, apply_constraints_all,
                      apply_gradient_norm_all, apply_gradient_normalization,
                      build_tx, fit_on_device_epochs, float_grad_leaves,
                      hyperparam_conf)
from .compile_cache import shared_jit, topology_signature
from .dispatch import DispatchWindow
from .conf.multi_layer import MultiLayerConfiguration
from .conf.schedules import resolve as resolve_schedule
from .conf.updaters import Sgd, UpdaterConf
from .layers.base import BaseLayerConf
from ..data.pipeline import ETL_BUCKETS as _ETL_BUCKETS
from ..data.shapes import _pad_time, default_shape_policy
from ..observability.clock import monotonic_s, wall_s
from ..observability.registry import default_registry
from ..train.listeners import TrainingListener

# training-step histogram bounds: sub-ms CPU steps up to multi-second
# XLA compiles in the "compile" phase series
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _on_device(a):
    """Device placement for one batch leaf; a leaf the input pipeline
    already placed (``DevicePrefetchIterator``) passes through untouched —
    no second H2D copy, no resharding."""
    if a is None or isinstance(a, jax.Array):
        return a
    return jnp.asarray(a)


class _StepForensics:
    """Per-step flight-recorder + health-monitor feed for the fit loops
    (shared by MultiLayerNetwork and ComputationGraph), amortized.

    Processing a step — a recorder dict build plus the monitor's EWMA
    updates — is only a few microseconds warm, but the train loop runs
    that Python cache-cold right after each multi-ms XLA dispatch, which
    inflates every call ~4x and blows the <2% overhead budget on small
    steps.  So :meth:`step` only captures a raw tuple (and, every
    ``grad_check_every``-th step, a *reference* to the still-on-device
    grad stats — the host fetch is deferred too) and :meth:`flush`
    drains the buffer through ``record()``/``observe_step()`` in a tight
    warm loop every ``FLUSH_EVERY`` steps.

    The loss is only materialized per step (``float`` = host sync) when
    a health MONITOR is armed: its NaN/stop/checkpoint reaction is
    contractually same-step, so that configuration pays the sync it
    always paid, and a non-finite loss still flushes IMMEDIATELY.
    Recorder-only forensics buffer the still-async device scalar and
    materialize at flush time — by then the value has long computed, so
    the D2H copy no longer stalls the dispatch pipeline (the lifetime
    audit's host-sync sweep; see tools/graftaudit).  Every dump path
    flushes first: the fit loops flush on exception and in their
    ``finally``, and the checkpointer's preemption dump calls the
    ``pre_dump`` hook this helper installs — buffered steps can never
    miss an artifact."""

    FLUSH_EVERY = 16
    __slots__ = ("net", "rec", "ring", "mon", "ckpt", "pol", "_buf",
                 "_grad_every", "_wall0", "_saved_kinds")

    def __init__(self, net, rec, mon, ckpt):
        self.net = net
        self.rec = rec if (rec is not None and rec.enabled) else None
        self.ring = self.rec.channel("train") \
            if self.rec is not None else None
        self.mon = mon
        self.ckpt = ckpt
        pol = getattr(net, "shape_policy", None)
        self.pol = pol if hasattr(pol, "last_pad_ratio") else None
        self._grad_every = mon.config.grad_check_every \
            if mon is not None else 0
        # wall = mono + _wall0: record timestamps derive from the step
        # end the loop already clocked, saving a wall read per step
        self._wall0 = wall_s() - monotonic_s()
        self._buf: list = []
        self._saved_kinds: set = set()
        if ckpt is not None:
            ckpt.pre_dump = self.flush

    def step(self, ep: int, seq: int, compile_step: bool,
             dt: float, t_end: float) -> bool:
        """Capture one fitted step (``t_end`` = the loop's monotonic
        step-end read); returns True when the monitor's opt-in
        ``stop_training`` policy says to halt the fit."""
        net = self.net
        loss = net._score
        mon = self.mon
        if mon is not None:
            # the monitor's same-step NaN reaction needs the value NOW;
            # recorder-only runs keep the device scalar async
            loss = float(loss)
        every = self._grad_every
        pol = self.pol
        buf = self._buf
        buf.append(
            (t_end, net.iteration, ep, seq, net.last_batch_size,
             loss, dt, compile_step,
             net._last_grad_stats
             if every > 0 and net.iteration % every == 0 else None,
             pol.last_pad_ratio if pol is not None else None))
        # loss - loss is 0.0 for finite loss, NaN for nan/±inf: the
        # non-finite check without a function call (monitor-armed only —
        # on the async path the check itself would be the host sync)
        if len(buf) >= self.FLUSH_EVERY or \
                (mon is not None and loss - loss != 0.0):
            return self.flush()
        return False

    def flush(self) -> bool:
        """Drain buffered steps into the recorder ring and the monitor;
        returns the monitor's stop verdict."""
        buf = self._buf
        mon = self.mon
        if not buf:
            return mon.should_stop() if mon is not None else False
        self._buf = []
        rec, ckpt, ring = self.rec, self.ckpt, self.ring
        wall0 = self._wall0
        for t_end, it, ep, seq, bs, loss, dt, comp, gref, pad in buf:
            # recorder-only steps buffered the async device scalar; one
            # cheap D2H each at drain time (the value computed steps ago).
            # NOT exception-guarded: this float() is where deferred
            # device-side failures first surface, and they must propagate
            loss = float(loss)
            if ring is not None:
                # literal-dict append onto the hoisted ring: same record
                # shape record() builds, minus the wrapper overhead
                ring.append({"ts": wall0 + t_end, "type": "step",
                             "iteration": it, "epoch": ep, "score": loss,
                             "batch": bs, "step_s": round(dt, 6),
                             "compile": comp})
            if mon is None:
                continue
            grad_norm = None
            if gref is not None:
                try:
                    grad_norm = float(gref["global_norm"])
                except (KeyError, TypeError, ValueError):
                    grad_norm = None
            eps = bs / dt if dt > 0 and not comp else None
            detections = mon.observe_step(
                loss=loss, grad_norm=grad_norm, examples_per_sec=eps,
                padding_ratio=pad, step=it)
            if detections and ckpt is not None and \
                    mon.config.checkpoint_on_detection and \
                    ckpt.manager is not None and \
                    any(d.kind not in self._saved_kinds
                        for d in detections):
                self._saved_kinds.update(d.kind for d in detections)
                try:
                    # ONE immediate save per detection kind marks the
                    # incident step durably without letting a sticky NaN
                    # (re-detected every dedupe_s) rotate the manager's
                    # keep_last window past every pre-incident checkpoint
                    ckpt._save(ep, seq)
                    mon.checkpoint_saves += 1
                except Exception:
                    pass   # a failed emergency save must not kill the fit
        if rec is not None:
            rec.snapshot_metrics()   # internally time-throttled
        return mon.should_stop() if mon is not None else False

Array = jax.Array


def _layer_confs(conf) -> Dict[str, Any]:
    return {f"layer_{i}": lc for i, lc in enumerate(conf.layers)}


def _cast_act(h, dtype: Optional[str]):
    """Cast a floating activation to a policy dtype (ints — token ids —
    pass through untouched)."""
    if dtype is None or not hasattr(h, "dtype") or \
            not jnp.issubdtype(h.dtype, jnp.floating) or \
            str(h.dtype) == dtype:
        return h
    return h.astype(dtype)


def _stack_forward(conf, params, state, x, *, train: bool, key, mask=None,
                   to_layer: Optional[int] = None, collect: bool = False,
                   carries: Optional[Dict[str, Any]] = None,
                   return_mask: bool = False, precision=None):
    """Trace the layer stack; returns (final_activation_or_list, new_state).

    A free function over the *configuration* — it must never touch a
    network instance, so the jitted programs built from it can live in the
    process-global trace cache and serve every equal-topology network
    (clones, master replicas).

    carries: optional dict of recurrent-layer carries keyed ``layer_i``
    (tBPTT chunk state / rnnTimeStep streaming state). When given, a dict
    of the same shape is written back into ``carries`` (callers pass a
    mutable dict and read the updated entries).

    precision: resolved ``PrecisionPolicy`` for mixed-precision walks
    (the train step passes it; inference/score paths keep their
    full-precision numerics and pass None).

    Homogeneous layer runs (identical confs repeated — a deep transformer
    stack) execute under ``jax.lax.scan`` so the program traces ONE layer
    body instead of N (``nn/scan_layers``); everything else walks
    unrolled, bit-identically to the pre-scan code.
    """
    layers = conf.layers
    n = len(layers) if to_layer is None else to_layer
    remat = bool(train and conf.defaults.get("cache_mode") == "remat")
    runs = dict(_scan_layers.scan_runs(
        conf, n, mask_present=mask is not None,
        carries_present=carries is not None, collect=collect,
        policy=precision))
    new_state = dict(state)
    acts = []
    h = x
    i = 0
    while i < n:
        lc = layers[i]
        pp = conf.preprocessor(i)
        if pp is not None:
            h = pp.pre_process(h, mask)
            if mask is not None:
                itype = conf.layer_input_types[i] if conf.layer_input_types \
                    else None
                mask = pp.feed_forward_mask(mask, itype)
        if precision is not None:
            h = _cast_act(h, precision.layer_dtype(lc))
        stop = runs.get(i)
        if stop is not None:
            # homogeneous run [i, stop): ONE traced body under lax.scan
            h, run_states = _scan_layers.run_scan(
                lc, [params.get(f"layer_{j}", {}) for j in range(i, stop)],
                [state.get(f"layer_{j}", {}) for j in range(i, stop)],
                h, key, i, train=train, mask=mask, remat=remat)
            for off, ls in enumerate(run_states):
                new_state[f"layer_{i + off}"] = ls
            i = stop
            continue
        lkey = jax.random.fold_in(key, i) if key is not None else None
        variables = {"params": params.get(f"layer_{i}", {}),
                     "state": state.get(f"layer_{i}", {})}
        lname = f"layer_{i}"
        if carries is not None and getattr(lc, "HAS_CARRY", False):
            h, new_carry = lc.apply_with_carry(
                variables, h, carries.get(lname), train=train, key=lkey,
                mask=mask)
            carries[lname] = new_carry
            lstate = variables.get("state", {})
        elif remat:
            # rematerialize per-layer activations on the backward pass
            # (the WorkspaceMode/CacheMode role: trade FLOPs for HBM)
            def _apply(vv, hh, kk, mm, _lc=lc):
                return _lc.apply(vv, hh, train=True, key=kk, mask=mm)
            h, lstate = jax.checkpoint(_apply)(variables, h, lkey, mask)
        else:
            h, lstate = lc.apply(variables, h, train=train, key=lkey,
                                 mask=mask)
        new_state[lname] = lstate
        if mask is not None:
            mask = lc.feed_forward_mask(mask, None)
        if collect:
            acts.append(h)
        i += 1
    out = acts if collect else h
    if return_mask:
        return out, new_state, mask
    return out, new_state


def _stack_loss(conf, params, state, x, y, *, train: bool, key, mask=None,
                label_mask=None, carries=None, precision=None):
    """Forward to last layer's loss + regularization (reference
    computeGradientAndScore, MultiLayerNetwork.java:2206).  Free function
    over the configuration — see ``_stack_forward``."""
    layers = conf.layers
    n = len(layers)
    h, new_state, pmask = _stack_forward(
        conf, params, state, x, train=train, key=key, mask=mask,
        to_layer=n - 1, carries=carries, return_mask=True,
        precision=precision)
    out_conf = layers[-1]
    if not hasattr(out_conf, "compute_loss"):
        raise ValueError(
            f"last layer '{out_conf.name}' is not an output layer")
    pp = conf.preprocessor(n - 1)
    if pp is not None:
        h = pp.pre_process(h, mask)
    if precision is not None:
        # the head's matmul runs in the compute dtype; the fused
        # softmax/loss reductions upcast to f32 inside nn/losses
        h = _cast_act(h, precision.layer_dtype(out_conf))
    lkey = jax.random.fold_in(key, n - 1) if key is not None else None
    variables = {"params": params.get(f"layer_{n-1}", {}),
                 "state": state.get(f"layer_{n-1}", {})}
    # label mask defaults to the PROPAGATED feature mask (reference
    # per-timestep masking when labelsMask is absent; a LastTimeStep/
    # global-pooling layer consumes the time axis and nulls the mask)
    lm = label_mask if label_mask is not None else pmask
    loss = out_conf.compute_loss(variables, h, y, train=train, key=lkey,
                                 mask=lm)
    # accumulator follows the LOSS dtype: a dtype-defaulted zeros(())
    # is f64 under x64 and silently promotes the whole loss output
    # (graftaudit AX001); f64 gradient-check runs still get f64 here
    # because their loss is already f64
    reg = jnp.zeros((), dtype=loss.dtype)
    for i, lc in enumerate(layers):
        lp = params.get(f"layer_{i}", {})
        if lp:
            reg = reg + lc.regularization_score(lp)
        if getattr(lc, "AUX_LOSS", False):
            aux = new_state.get(f"layer_{i}", {}).get("aux_loss")
            if aux is not None:
                reg = reg + aux
    return loss + reg, new_state


def _build_stack_fn(conf, tx, kind: str):
    """Build the Python function behind one jitted entry point.

    Returns ``(fun, donate_argnums)``.  Every closure here captures only
    ``conf``/``tx`` — structural configuration shared by all equal-signature
    networks — never a network instance, which is what makes the functions
    safe to place in the process-global trace cache (and is exactly the
    hazard graftlint JX013 flags).
    """
    if kind == "output":
        def fn(params, state, x):
            return _stack_forward(conf, params, state, x, train=False,
                                  key=None)
        return fn, ()
    if kind == "serve":
        # the serving engine's forward: identical program to "output" but
        # with the input batch donated — the engine builds a fresh padded
        # device batch per dispatch and never rereads it, so XLA may alias
        # the buffer into activations (one less live HBM copy per batch).
        # CPU doesn't implement donation and warns per compile; skip there
        # (graftaudit AX005 audits this contract; the CPU skip is a
        # justified manifest suppression in tools/graftaudit/canonical.py).
        def fn(params, state, x):
            return _stack_forward(conf, params, state, x, train=False,
                                  key=None)
        return fn, (() if jax.default_backend() == "cpu" else (2,))
    if kind == "output_train":
        def fn(params, state, x, key):
            return _stack_forward(conf, params, state, x, train=True,
                                  key=key)
        return fn, ()
    if kind == "score":
        def fn(params, state, x, y, label_mask):
            return _stack_loss(conf, params, state, x, y, train=False,
                               key=None, label_mask=label_mask)
        return fn, ()
    if kind == "rnn_time_step":
        def fn(params, state, x, carries):
            carries = dict(carries)
            y, _ = _stack_forward(conf, params, state, x, train=False,
                                  key=None, carries=carries)
            return y, carries
        return fn, ()
    if kind == "train_step":
        # maximal donation (graftaudit AX007): params/state/opt-state AND
        # the RNG key are dead after the call — the fused-RNG step returns
        # the successor key as an alias-matched output, so the 8-byte key
        # buffer recycles in place like the training carry does
        return _build_train_step(conf, tx, False), (0, 1, 2, 3)
    if kind == "train_step_carry":
        # tBPTT additionally donates the recurrent carries (argnum 8):
        # each chunk's carries are consumed by exactly one step
        return _build_train_step(conf, tx, True), (0, 1, 2, 3, 8)
    if kind in ("paged_prefill", "paged_decode"):
        # autoregressive generation programs (bucketed prompt-suffix
        # prefill + fixed-shape slot-batch decode through the paged
        # block pool): built in generation/programs.py, registered here
        # so they ride the same process-global trace cache, instance
        # _jit_cache lifetime, and compile counters as every other entry
        # point
        from ..generation.programs import build_generation_fn
        return build_generation_fn(conf, kind)
    raise KeyError(kind)


def _sparse_embedding_conf(conf):
    """The stack's sparse-gradient embedding layer, or None.

    Only the FIRST layer is eligible: the sparse pre-pass coalesces the
    raw batch ids before the traced stack runs, and only layer_0's ids
    ARE the batch input.  A ``sparse_grad=True`` anywhere else is a
    config error surfaced at build time, not a silent dense fallback.
    """
    from .layers.feedforward import EmbeddingLayer, EmbeddingSequenceLayer
    found = None
    # scan the WHOLE stack before returning: a flag on a later layer
    # must fail even when layer_0 is itself valid
    for i, lc in enumerate(conf.layers):
        if not getattr(lc, "sparse_grad", False):
            continue
        if i != 0:
            raise ValueError(
                f"layer '{lc.name}': sparse_grad=True requires the "
                "embedding to be the first layer (its ids must be the "
                "batch input for the densified pre-pass); position "
                f"{i} gets dense gradients — drop the flag there")
        if not isinstance(lc, (EmbeddingLayer, EmbeddingSequenceLayer)):
            raise ValueError(
                f"layer '{lc.name}': sparse_grad is an embedding-layer "
                "contract")
        if float(lc.resolved("l1", 0.0) or 0.0) or \
                float(lc.resolved("l2", 0.0) or 0.0):
            raise ValueError(
                f"layer '{lc.name}': sparse_grad=True with l1/l2 on the "
                "table is unsupported — dense weight decay touches every "
                "row, defeating the touched-rows-only exchange; drop the "
                "regularization or the flag")
        found = lc
    return found


def _build_train_step(conf, tx, with_carry: bool):
    gn_mode = conf.defaults.get("gradient_normalization")
    gn_thr = float(conf.defaults.get("gradient_normalization_threshold", 1.0))
    pol = _precision.resolve(conf.defaults)
    confs = _layer_confs(conf)
    sparse_emb = _sparse_embedding_conf(conf)
    # per-layer compute dtypes, resolved once at build time (keep_f32
    # classes and per-name overrides stay f32 — their params are never
    # downcast, and _stack_forward casts activations to match)
    cast_map = {}
    if pol is not None:
        for name, lc in confs.items():
            dt = pol.layer_dtype(lc)
            if dt not in (None, "float32"):
                cast_map[name] = dt

    def step(params, state, opt_state, key, x, y, mask, label_mask,
             carries=None):
        # fused RNG succession: the split that used to run host-side
        # (``self._rng, key = jax.random.split(self._rng)``) happens
        # inside the program — bit-identical key sequence, one less
        # device dispatch per step, and the key argument gains an
        # alias-matched output (``new_rng``) so it can be donated
        new_rng, key = jax.random.split(key)
        if pol is not None:
            # floating inputs only: integer token ids must reach the
            # embedding gather exact (a bf16 cast quantizes ids > 256)
            x = _cast_act(x, pol.compute_dtype)
        # sparse-embedding pre-pass (nn/sparse): coalesce the batch's
        # touched table rows OUTSIDE the differentiated function and
        # substitute (table -> gathered rows, ids -> row slots), so the
        # table's cotangent is [capacity, dim] — the dense [vocab, dim]
        # cotangent never exists in this program.  All decisions here
        # are trace-time static (dtype/shape/conf), so the compiled
        # program is fixed per batch signature: zero steady recompiles.
        ctx = None
        if sparse_emb is not None:
            W0 = params["layer_0"]["W"]
            ids = sparse_emb.decode_ids(x)
            if ids is None:
                # never a silent dense fallback: falling through here
                # would quietly restore the O(vocab·dim) exchange the
                # flag exists to remove
                raise ValueError(
                    f"layer '{sparse_emb.name}': sparse_grad=True needs "
                    "an integer id batch for the densified pre-pass, but "
                    f"this input (shape {tuple(x.shape)}, dtype "
                    f"{x.dtype}) rides the one-hot path — feed ids "
                    "(argmax the one-hots upstream), or drop sparse_grad")
            if not _sparse.table_is_unambiguous(params, W0.shape):
                raise ValueError(
                    f"layer '{sparse_emb.name}': another parameter leaf "
                    f"shares the table's exact shape {tuple(W0.shape)} — "
                    "the row-space mirror walk is shape-keyed and cannot "
                    "disambiguate the updater mirrors; resize/split the "
                    "twin parameter or drop sparse_grad")
            ctx = _sparse.RowContext(
                W0, ids, sparse_emb.sparse_grad_capacity)
        if ctx is not None:
            params_in = {**params, "layer_0": dict(params["layer_0"],
                                                   W=ctx.rows_ext)}
            x_in = ctx.x_sub
        else:
            params_in, x_in = params, x
        ls = state.get(_precision.SCALE_STATE_KEY) \
            if pol is not None and pol.scaled else None
        scale = ls["scale"] if ls is not None else None

        def loss_fn(p):
            if cast_map:
                # mixed precision: cast params per layer for the traced
                # stack; grads w.r.t. the f32 masters accumulate in f32
                # (the cast is part of the differentiated program)
                p = {k: (_cast_floats(v, cast_map[k]) if k in cast_map
                         else v) for k, v in p.items()}
            if with_carry:
                # carry state flows INTO the chunk; gradients do not flow
                # back across the chunk boundary (tBPTT truncation).
                cs = dict(jax.tree_util.tree_map(jax.lax.stop_gradient,
                                                 carries))
                loss, new_state = _stack_loss(
                    conf, p, state, x_in, y, train=True, key=key,
                    mask=mask, label_mask=label_mask, carries=cs,
                    precision=pol)
            else:
                cs = None
                loss, new_state = _stack_loss(
                    conf, p, state, x_in, y, train=True, key=key,
                    mask=mask, label_mask=label_mask, precision=pol)
            # loss scaling happens on the objective so the whole backward
            # pass sees scaled gradients (fp16 underflow protection); the
            # reported loss stays unscaled
            obj = loss * scale if scale is not None else loss
            return obj, (loss, new_state, cs)
        (_obj, (loss, new_state, new_carries)), grads = \
            jax.value_and_grad(loss_fn, has_aux=True)(params_in)
        if ctx is not None:
            # the densified carrier: coalesced row indices + values (the
            # custom-vjp lookup's segment-summed cotangent), in place of
            # a dense table gradient
            grads = dict(grads)
            grads["layer_0"] = dict(grads["layer_0"],
                                    W=ctx.wrap_grad(grads["layer_0"]["W"]))
        finite = None
        if scale is not None:
            grads, finite = _precision.unscale_and_check(grads, scale)
        grads = apply_gradient_norm_all(grads, confs, gn_mode, gn_thr)
        # per-iteration gradient stats for listeners (reference
        # ParamAndGradientIterationListener / StatsListener): computed
        # inside the same program so they fuse with the update.  Float
        # leaves only (_common.float_grad_leaves): SparseRows carries
        # int32 indices, and coalesced values give the SAME norm the
        # dense gradient would.
        gleaves = float_grad_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in gleaves)) \
            if gleaves else jnp.zeros((), jnp.float32)
        glayer = {k: jnp.sqrt(sum(jnp.sum(g * g)
                                  for g in float_grad_leaves(v)))
                  for k, v in grads.items() if v}
        if ctx is not None:
            # lazy row-space update: the SAME optax transform runs on
            # [capacity, dim] views — touched rows of the table and of
            # every param-shaped mirror leaf (mu/nu/trace) — then only
            # those rows scatter back.  Untouched rows and mirrors keep
            # their pre-step bytes.
            g_upd = dict(grads)
            g_upd["layer_0"] = dict(g_upd["layer_0"],
                                    W=g_upd["layer_0"]["W"].values)
            p_upd = {**params, "layer_0": dict(params["layer_0"],
                                               W=ctx.rows)}
            opt_upd = _sparse.gather_rows_tree(opt_state, ctx)
        else:
            g_upd, p_upd, opt_upd = grads, params, opt_state
        updates, new_opt = tx.update(g_upd, opt_upd, p_upd)
        new_params = optax.apply_updates(p_upd, updates)
        if ctx is not None:
            new_params = {**new_params, "layer_0": dict(
                new_params["layer_0"],
                W=ctx.scatter_rows(params["layer_0"]["W"],
                                   new_params["layer_0"]["W"]))}
            new_opt = _sparse.scatter_rows_tree(opt_state, new_opt, ctx)
        new_params = apply_constraints_all(new_params, confs)
        if pol is not None:
            # keep running state (BN statistics) in f32 so the step's
            # input/output treedefs+dtypes stay fixed across iterations
            new_state = _cast_floats(new_state, jnp.float32,
                                     only=pol.compute_dtype)
        gstats = {"global_norm": gnorm, "layer_norms": glayer}
        if ctx is not None:
            # observability: how many real table rows this step exchanged
            # (vs the static capacity) — the densification win, visible
            # to listeners without a host sync
            gstats["embedding_rows_touched"] = ctx.touched()
        if ls is not None:
            new_params, new_opt, new_state, sel = _precision.overflow_skip(
                pol, ls, finite, params, new_params, opt_state, new_opt,
                state, new_state, gstats)
            if with_carry:
                # the overflowed forward also poisoned the recurrent
                # carries — a skipped chunk must hand the NEXT chunk its
                # pre-step carries, or one overflow taints the rest of
                # the sequence
                new_carries = sel(new_carries, carries)
        if with_carry:
            return (new_params, new_state, new_opt, new_rng, loss, gstats,
                    new_carries)
        return new_params, new_state, new_opt, new_rng, loss, gstats

    return step


def _build_pretrain_step(conf, tx, i: int):
    """Pretrain step for layer ``i``: the frozen prefix and running state
    ride in as ARGUMENTS (the old per-call closure baked them in as trace
    constants — stale after any host-side update, and re-jitted per call)."""
    lc = conf.layers[i]

    def step(p_i, opt_state, key, x, frozen, state):
        # fused RNG succession (see _build_train_step): the host-side
        # split moves into the program; the successor key is returned
        new_rng, key = jax.random.split(key)

        def loss_fn(pp):
            feats = x
            if i > 0:
                all_p = dict(frozen)
                all_p[f"layer_{i}"] = pp
                feats, _ = _stack_forward(conf, all_p, state, x,
                                          train=False, key=None, to_layer=i)
            variables = {"params": pp,
                         "state": state.get(f"layer_{i}", {})}
            return lc.pretrain_loss(variables, feats, key=key, train=True)
        loss, grads = jax.value_and_grad(loss_fn)(p_i)
        updates, new_opt = tx.update(grads, opt_state, p_i)
        return optax.apply_updates(p_i, updates), new_opt, new_rng, loss

    return step


class MultiLayerNetwork:
    """Sequential network: init → fit/output/score/evaluate."""

    def __init__(self, conf: MultiLayerConfiguration):
        conf.resolve()
        self.conf = conf
        self.layers = conf.layers
        self.params: Dict[str, Any] = {}
        self.state: Dict[str, Any] = {}
        self.opt_state = None
        self.iteration = 0
        self.epoch = 0
        self.last_batch_size = 0
        self.listeners: List[TrainingListener] = []
        self._score = float("nan")
        # drain-boundary telemetry (nn/dispatch.DispatchWindow): the last
        # materialized step's score/iteration — what rate/score listeners
        # read mid-fit without forcing their own host sync
        self.last_drained_score = float("nan")
        self.last_drained_iteration = -1
        self._tx = None
        self._rng = jax.random.PRNGKey(conf.seed)
        # instance view over the process-global trace cache: holds strong
        # refs to the shared jitted entries this network uses (the global
        # cache is weak-valued, so these refs ARE the entries' lifetime)
        self._jit_cache: Dict[Any, Any] = {}
        self._topo_sig: Optional[str] = None
        self._pad_safe: Optional[bool] = None
        self.shape_policy = default_shape_policy()
        self._rnn_carries = None
        self._rnn_carry_batch = -1
        # embedding-first boundary validation cache: None = undecided,
        # False = no id layer, else the layer conf
        self._id_layer = None
        # did the most recent train step (re)trace?  Read from the shared
        # InstrumentedJit after each step: the metrics split
        # (training_step_seconds{phase=compile|steady}) keys off the REAL
        # trace events, so a clone's cache-hit first step reads steady and
        # a mid-fit retrace (new shape/treedef) reads compile
        self._last_step_traced = False
        # per-fit StepProfiler, attached by fit() so _fit_one can credit
        # its h2d/listener slices; None outside a profiled fit
        self._stepprof = None

    # ------------------------------------------------------------------ init
    def init(self) -> "MultiLayerNetwork":
        key = jax.random.PRNGKey(self.conf.seed)
        self.params, self.state = {}, {}
        for i, lc in enumerate(self.layers):
            key, sub = jax.random.split(key)
            itype = self.conf.layer_input_types[i] if self.conf.layer_input_types else None
            v = lc.init(sub, itype)
            self.params[f"layer_{i}"] = v.get("params", {})
            self.state[f"layer_{i}"] = v.get("state", {})
        ls = _precision.init_scale_state(
            _precision.resolve(self.conf.defaults))
        if ls is not None:
            # loss-scale carry rides the state pytree: donated through the
            # step, checkpointed, and averaged like any training state
            self.state[_precision.SCALE_STATE_KEY] = ls
        self._tx = self._build_tx()
        self.opt_state = self._tx.init(self.params)
        return self

    def _default_updater(self) -> UpdaterConf:
        u = self.conf.defaults.get("updater")
        return u if u is not None else Sgd(learning_rate=0.1)

    def _layer_conf_map(self):
        return {f"layer_{i}": lc for i, lc in enumerate(self.layers)}

    def _build_tx(self) -> optax.GradientTransformation:
        """One optax transform; per-layer overrides via multi_transform
        (the reference's per-UpdaterBlock machinery,
        ``nn/updater/BaseMultiLayerUpdater.java:64-138``)."""
        return build_tx(self._default_updater(), self._layer_conf_map(),
                        self.params)

    # -------------------------------------------------------------- forward
    def _forward(self, params, state, x, *, train: bool, key, mask=None,
                 to_layer: Optional[int] = None, collect: bool = False,
                 carries: Optional[Dict[str, Any]] = None,
                 return_mask: bool = False):
        """Delegate to the conf-parameterized ``_stack_forward`` (kept as a
        method for external callers: solvers, gradient checks,
        TransferLearningHelper)."""
        return _stack_forward(self.conf, params, state, x, train=train,
                              key=key, mask=mask, to_layer=to_layer,
                              collect=collect, carries=carries,
                              return_mask=return_mask)

    def _loss(self, params, state, x, y, *, train: bool, key, mask=None,
              label_mask=None, carries=None):
        """Delegate to the conf-parameterized ``_stack_loss``."""
        return _stack_loss(self.conf, params, state, x, y, train=train,
                           key=key, mask=mask, label_mask=label_mask,
                           carries=carries)

    # ---------------------------------------------------------- public API
    def output(self, x, train: bool = False) -> Array:
        """Forward pass (reference ``output(INDArray, train)``). train=True
        keeps stochastic regularization (dropout) active — MC-dropout style.

        Inference batches route through the shape policy: a ragged eval
        batch pads up to an already-compiled bucket and the padded rows are
        sliced off the result (row-wise inference programs make this
        value-preserving; ``train=True`` skips padding — stochastic draws
        and BN batch statistics are shape-dependent)."""
        self._validate_input_ids(x)
        x = jnp.asarray(x)
        pol = self.shape_policy
        n = -1
        if not train and pol is not None and pol.enabled and \
                getattr(x, "ndim", 1) >= 2 and self._pad_output_safe():
            x, n = pol.pad_eval_rows(x)
        if train:
            fn = self._get_jitted("output_train")
            self._rng, key = jax.random.split(self._rng)
            y, _ = fn(self.params, self.state, x, key)
        else:
            fn = self._get_jitted("output")
            y, _ = fn(self.params, self.state, x)
        if n >= 0 and getattr(y, "shape", (0,))[0] > n:
            y = y[:n]
        return y

    def feed_forward(self, x, train: bool = False) -> List[Array]:
        """All layer activations (reference ``feedForward``). train=True keeps
        stochastic regularization active (fresh RNG draw per call)."""
        key = None
        if train:
            self._rng, key = jax.random.split(self._rng)
        acts, _ = self._forward(self.params, self.state, jnp.asarray(x),
                                train=train, key=key, collect=True)
        return acts

    def score(self, dataset=None, x=None, y=None) -> float:
        """Loss on a dataset; with no arguments, the score of the most recent
        training minibatch (reference ``score()`` / ``score(DataSet)``)."""
        if dataset is None and x is None:
            return float(self._score)   # device scalar mid-fit_on_device
        if dataset is not None:
            x, y, _, _ = self._normalize_batch(dataset)
        self._validate_input_ids(x)
        x, y = jnp.asarray(x), jnp.asarray(y)
        lm = None
        pol = self.shape_policy
        if pol is not None and pol.enabled and self._pad_eval_safe():
            # ragged scoring batches ride an already-compiled bucket with
            # the padded rows masked out of the loss (exact: the masked
            # mean's denominator counts only rows with mask weight)
            x, y, lm = pol.pad_score_batch(x, y)
        fn = self._get_jitted("score")
        loss, _ = fn(self.params, self.state, x, y, lm)
        return float(loss)

    def _topology_sig(self) -> str:
        if self._topo_sig is None:
            self._topo_sig = topology_signature(self.conf)
        return self._topo_sig

    def invalidate_compile_cache(self) -> "MultiLayerNetwork":
        """Drop this network's compiled-function views and re-derive its
        topology signature.  Call after mutating ``conf``/layer confs IN
        PLACE (transfer-learning fine-tune on a live net, BN folding);
        builder-style APIs that construct a fresh network need nothing —
        the edited conf signs differently and lands in its own cache slot.
        """
        self._jit_cache = {}
        self._topo_sig = None
        self._pad_safe = None
        self._id_layer = None
        return self

    def _get_jitted(self, kind: str):
        fn = self._jit_cache.get(kind)
        if fn is None:
            if self._tx is None and kind in ("train_step",
                                             "train_step_carry"):
                self._tx = self._build_tx()
            fn = shared_jit(
                (type(self).__name__, self._topology_sig(), kind),
                lambda: _build_stack_fn(self.conf, self._tx, kind),
                name=kind)
            self._jit_cache[kind] = fn
        return fn

    def _validate_input_ids(self, x):
        """Host-side id-range validation for embedding-first networks
        at the fit/output/score boundary (the traced gather clamps
        out-of-range ids silently; see ``feedforward.validate_host_ids``
        — device-resident and float/one-hot batches pass through)."""
        lc0 = self._id_layer
        if lc0 is None:
            from .layers.feedforward import (EmbeddingLayer,
                                             EmbeddingSequenceLayer)
            lc = self.layers[0] if self.layers else None
            lc0 = lc if isinstance(
                lc, (EmbeddingLayer, EmbeddingSequenceLayer)) else False
            self._id_layer = lc0
        if lc0:
            from .layers.feedforward import validate_host_ids
            validate_host_ids(lc0, x)

    def _pad_flags(self):
        if self._pad_safe is None:
            from .layers.normalization import BatchNormalization
            # an AUX_LOSS layer (MoE) couples rows even at inference:
            # padded rows compete for expert CAPACITY, shifting real rows'
            # routing, and its load-balancing loss term is computed from
            # the whole batch (the label mask cannot silence padded rows)
            row_indep = all(not getattr(lc, "AUX_LOSS", False)
                            for lc in self.layers)
            eval_safe = row_indep and (
                not self.layers or getattr(self.layers[-1],
                                           "SUPPORTS_LOSS_MASK", True))
            # BatchNorm additionally trains on batch statistics, which
            # padded rows would perturb (eval uses running stats: safe)
            train_safe = eval_safe and all(
                not isinstance(hyperparam_conf(lc) or lc,
                               BatchNormalization) for lc in self.layers)
            self._pad_safe = (row_indep, eval_safe, train_safe)
        return self._pad_safe

    def _pad_output_safe(self) -> bool:
        """output() padding only needs row-independent inference."""
        return self._pad_flags()[0]

    def _pad_eval_safe(self) -> bool:
        """Loss-path (score) padding additionally needs a mask-honoring
        head — see data/shapes.py."""
        return self._pad_flags()[1]

    def _pad_train_safe(self) -> bool:
        """Training padding additionally requires no cross-batch layers
        (BatchNorm batch statistics)."""
        return self._pad_flags()[2]

    def fit(self, data=None, labels=None, *, epochs: int = 1,
            mask=None, label_mask=None, checkpoint=None,
            resume_from=None) -> "MultiLayerNetwork":
        """Train. ``data`` may be (x, y) arrays or an iterable of batches
        (the DataSetIterator role).

        ``checkpoint``: a ``faulttolerance.CheckpointConfig`` — periodic
        crash-consistent saves (params + updater + RNG + data cursor +
        shape-policy buckets), optionally with a SIGTERM save-on-preempt
        hook.  ``resume_from``: a checkpoint directory / store /
        ``CheckpointManager`` — restores full training state and resumes
        mid-epoch at the exact saved batch cursor, reproducing the
        uninterrupted run's params (checkpointing is RNG-neutral, so runs
        with and without it are byte-identical)."""
        from ..data.dataset import DataSet
        if self.params == {}:
            self.init()
        if labels is not None:
            batches_factory = lambda: [(data, labels, mask, label_mask)]
        elif isinstance(data, DataSet):
            batches_factory = lambda: [self._normalize_batch(data)]
        elif isinstance(data, tuple) and len(data) in (2, 4):
            # fit((x, y)) single-batch form — must not be iterated as batches
            batches_factory = lambda: [self._normalize_batch(data)]
        elif hasattr(data, "reset") or hasattr(data, "__iter__"):
            if not hasattr(data, "reset") and epochs > 1 and iter(data) is data:
                # bare generator: can't be re-iterated per epoch; materialize
                data = [self._normalize_batch(b) for b in data]
                batches_factory = lambda: data
            else:
                src = data

                def batches_factory():
                    if hasattr(src, "reset"):
                        src.reset()
                    for b in src:
                        yield self._normalize_batch(b)
        else:
            raise ValueError("fit() needs (x, y) or an iterator")

        algo = self.conf.defaults.get("optimization_algo", "sgd")
        if algo not in ("sgd", "stochastic_gradient_descent"):
            if checkpoint is not None or resume_from is not None:
                raise ValueError(
                    "checkpoint=/resume_from= are only supported on the SGD "
                    f"path; optimization_algo='{algo}' routes through the "
                    "legacy solvers")
            # legacy full-batch solvers (reference Solver → LBFGS/CG/line
            # search, StochasticGradientDescent.java:58 being the default)
            from ..train.solvers import Solver
            solver = Solver(self, algo, max_iterations=int(
                self.conf.defaults.get("max_iterations", 100)))
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self)
                for batch in batches_factory():
                    x, y, m, lm = batch
                    self.last_batch_size = int(getattr(x, "shape", (0,))[0])
                    solver.optimize(x, y, mask=m, label_mask=lm)
                for lst in self.listeners:
                    lst.on_epoch_end(self)
                self.epoch += 1
            return self

        # constructed only after every validation raise above: the SIGTERM
        # hook it installs must always reach the loop's finally/close()
        ckpt = None
        if checkpoint is not None or resume_from is not None:
            from ..faulttolerance.checkpoint import FitCheckpointer
            ckpt = FitCheckpointer(self, checkpoint, resume_from)
        step_fn = self._get_jitted("train_step")
        # observability (cheap by default: plain host float math per
        # step, instruments resolved once per fit, and the step timing
        # closes on the loss sync _fit_one/_fit_tbptt already perform —
        # no extra device sync is ever forced here; a disabled registry
        # reduces all of it to one bool check)
        reg = default_registry()
        obs = reg.enabled
        # runtime forensics: the flight recorder keeps the recent-step
        # window for crash dumps; the health monitor (when installed)
        # watches the step signals for NaNs/spikes/throughput collapse
        from ..observability.health import get_health_monitor
        from ..observability.profiler import step_profiler_for
        from ..observability.recorder import get_flight_recorder
        rec = get_flight_recorder()
        rec_on = rec is not None and rec.enabled
        mon = get_health_monitor()
        forensics = _StepForensics(self, rec, mon, ckpt) \
            if (rec_on or mon is not None) else None
        # per-step phase attribution (etl/h2d/dispatch/device/listener/
        # forensics/checkpoint) with a SAMPLED device fence — steady
        # unsampled steps stay fully async (the host-sync sweep holds)
        prof = step_profiler_for("train_step")
        self._stepprof = prof

        # bounded async dispatch (ISSUE 18): the host may run up to
        # DL4J_TPU_DISPATCH_DEPTH (default 2) steps ahead of the device,
        # overlapping step N+1's ETL/padding/h2d/bookkeeping with step
        # N's execution.  Drains at epoch ends and checkpoint boundaries
        # keep exact-resume parity; every drained token is NaN-checked
        # with ITS OWN iteration so deferred device failures surface
        # within the window bound, correctly attributed.
        def _nan_at_drain(iteration, value):
            if rec_on:
                rec.record("train", "nan_at_drain", score=value,
                           iteration=int(iteration))
        win = DispatchWindow(owner=self, profiler=prof,
                             on_nan=_nan_at_drain)
        if obs:
            steps_c = reg.counter("training_steps_total",
                                  "Optimizer steps taken")
            examples_c = reg.counter("training_examples_total",
                                     "Training examples consumed")
            step_h = reg.histogram(
                "training_step_seconds",
                "Train step wall time, split compile vs steady",
                ("phase",), buckets=_STEP_BUCKETS)
            etl_fetch_h = reg.histogram(
                "training_etl_seconds",
                "Time blocked on the data pipeline per batch, by stage",
                ("stage",), buckets=_ETL_BUCKETS).labels("fetch")
            step_compile_h = step_h.labels("compile")
            step_steady_h = step_h.labels("steady")
        steady_examples, steady_s = 0, 0.0
        start_epoch = ckpt.start_epoch if ckpt is not None else 0
        stop = False
        try:
            for ep in range(start_epoch, epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self)
                batches = iter(batches_factory())
                # resume cursor: the first resumed epoch skips the batches
                # the checkpointed run already consumed (the data-pipeline
                # seq cursor) WITHOUT fitting or touching the RNG, so the
                # resumed stream lines up with the uninterrupted run's
                skip = ckpt.skip_batches \
                    if (ckpt is not None and ep == ckpt.start_epoch) else 0
                seq = 0
                while True:
                    t_etl = time.perf_counter()
                    batch = next(batches, None)
                    # ETL/compute boundary timing (reference lastEtlTime,
                    # MultiLayerNetwork.java:1203-1209): time blocked on the
                    # data pipeline, visible to PerformanceListener
                    self.last_etl_ms = (time.perf_counter() - t_etl) * 1e3
                    if batch is None:
                        break
                    if seq < skip:
                        seq += 1
                        continue
                    x, y, m, lm = batch
                    self.last_batch_size = int(getattr(x, "shape", (0,))[0])
                    t_step = monotonic_s()
                    if prof is not None:
                        prof.begin(t_step, self.last_etl_ms * 1e-3)
                    if self.conf.backprop_type == "tbptt" and \
                            getattr(x, "ndim", 2) == 3 and \
                            x.shape[1] > self.conf.tbptt_fwd_length:
                        self._fit_tbptt(step_fn, x, y, m, lm)
                    else:
                        self._fit_one(x, y, m, lm)
                    if prof is not None:
                        prof.dispatched(self._score, window=win)
                    compile_step = self._last_step_traced
                    t_end = monotonic_s()
                    dt = t_end - t_step
                    if obs:
                        (step_compile_h if compile_step
                         else step_steady_h).observe(dt)
                        etl_fetch_h.observe(self.last_etl_ms / 1e3)
                        steps_c.inc()
                        examples_c.inc(self.last_batch_size)
                        if not compile_step:
                            steady_examples += self.last_batch_size
                            steady_s += dt
                    seq += 1
                    if forensics is not None and \
                            forensics.step(ep, seq, compile_step, dt,
                                           t_end):
                        stop = True   # opt-in health stop: clean return
                    if prof is not None:
                        prof.lap("forensics")
                    if not stop and ckpt is not None:
                        if ckpt.due():
                            # checkpoint boundary: materialize the whole
                            # window so the save captures finished steps
                            # and mid-window resume stays digest-exact
                            win.drain()
                        if ckpt.after_batch(ep, seq):
                            stop = True   # SIGTERM: final save — return
                    if prof is not None:
                        if ckpt is not None:
                            prof.lap("checkpoint")
                        prof.end(self.iteration, compile_step)
                    if stop:
                        break
                    # admit this step into the in-flight window (blocks on
                    # the oldest step once the window is full — the
                    # bounded-pipeline backpressure point)
                    win.push(self._score, self.iteration)
                if stop:
                    break
                # ONE materialization per epoch (fit_on_device's sync
                # convention): steps pipelined async all epoch; epoch-end
                # listeners (MetricsListener score/grad-norm) see a host
                # float without forcing their own sync
                win.drain()
                self._score = float(self._score)
                if prof is not None:
                    prof.materialized()
                for lst in self.listeners:
                    lst.on_epoch_end(self)
                self.epoch += 1
                if ckpt is not None and ckpt.after_epoch(ep):
                    stop = True
                    break
            # stop-path exits (health stop, SIGTERM) break before the
            # epoch-end drain; materialize what's still in flight so the
            # drained-score bookkeeping is consistent on clean returns
            win.drain()
        except Exception as e:
            # never block on in-flight work while unwinding — the final
            # un-guarded float(_score) convention still surfaces deferred
            # device failures for callers that catch and continue
            win.abandon()
            # unhandled fit exception: commit the flight-recorder window
            # BEFORE propagating — the artifact that explains the crash
            # must exist even if the process dies on the way up
            if rec_on:
                if forensics is not None:
                    try:
                        forensics.flush()
                    except Exception:
                        pass   # forensics must not mask the real error
                rec.record("train", "fit_exception",
                           error=f"{type(e).__name__}: {e}",
                           iteration=int(self.iteration))
                rec.maybe_dump(
                    "fit_exception",
                    directory=(ckpt.manager.directory
                               if ckpt is not None and ckpt.manager
                               is not None else None))
            raise
        finally:
            if forensics is not None:
                try:
                    forensics.flush()
                except Exception:
                    pass
            if prof is not None:
                self._stepprof = None
                try:
                    prof.flush()
                except Exception:
                    pass   # profile telemetry must not mask the real error
            if ckpt is not None:
                ckpt.close()
        # ONE materialization for the whole fit: _fit_one keeps _score
        # as the async device scalar so steps pipeline.  NOT
        # exception-guarded: this float() is where deferred device-side
        # failures first surface, and they must propagate
        self._score = float(self._score)
        if obs and steady_s > 0:
            # steady-state throughput: the compile-dominated first step
            # is excluded (same convention as utils/benchmarks.py)
            reg.gauge("training_examples_per_sec",
                      "Training examples/sec over the last fit() "
                      "(compile excluded where the path can tell)"
                      ).set(steady_examples / steady_s)
        return self

    def fit_on_device(self, x, y, *, batch_size: int, epochs: int = 1,
                      shuffle: bool = True, checkpoint=None,
                      resume_from=None) -> "MultiLayerNetwork":
        """Device-resident epoch training: the whole dataset lives in HBM and
        ONE jitted program scans the train step across all minibatches, so an
        epoch costs a single dispatch.

        TPU-first counterpart of the reference's prefetching iterator stack
        (``AsyncDataSetIterator`` hides host ETL latency behind compute;
        here nothing crosses the host boundary at all, which also removes
        per-step dispatch latency — decisive on remote-attached devices).
        Use plain ``fit`` when data exceeds HBM or per-iteration listener
        granularity matters: listeners here fire once per epoch with the
        recorded final-batch score (per-step hooks would force host syncs).

        ``checkpoint``/``resume_from`` (``faulttolerance``): epoch-boundary
        crash-consistent saves and exact epoch-granular resume.  A
        checkpoint config pins the per-epoch dispatch path (the fused
        multi-epoch program has no epoch boundaries to save at).
        """
        if self.params == {}:
            self.init()
        if self.conf.backprop_type == "tbptt":
            raise ValueError(
                "fit_on_device does not support tBPTT (the scanned step has "
                "no carry truncation); use fit()")
        algo = self.conf.defaults.get("optimization_algo", "sgd")
        if algo not in (None, "sgd", "stochastic_gradient_descent"):
            raise ValueError(
                f"fit_on_device requires the SGD path; optimization_algo="
                f"'{algo}' routes through the legacy solvers — use fit()")
        # constructed only after the validation raises above (its SIGTERM
        # hook must always reach fit_on_device_epochs' finally/close())
        ckpt = None
        if checkpoint is not None or resume_from is not None:
            from ..faulttolerance.checkpoint import FitCheckpointer
            ckpt = FitCheckpointer(self, checkpoint, resume_from)
        step = self._get_jitted("train_step")
        return fit_on_device_epochs(
            self, [jnp.asarray(x)], [jnp.asarray(y)], batch_size, epochs,
            shuffle,
            call_step=lambda p, s, o, k, bx, by: step(p, s, o, k, bx[0],
                                                      by[0], None, None),
            fit_tail=lambda xt, yt: self._fit_one(xt[0], yt[0], None, None),
            ckpt=ckpt)

    def _fit_tbptt(self, step_fn, x, y, mask, label_mask):
        """Truncated BPTT (reference ``doTruncatedBPTT``,
        MultiLayerNetwork.java:1393): split the time axis into
        tbptt_fwd_length chunks; recurrent state (h, c) carries across chunk
        boundaries with gradients stopped at each boundary — so the backward
        window equals the forward chunk, the reference's default fwd==back
        configuration.  ``tbptt_back_length`` is accepted for config parity.
        """
        del step_fn  # tbptt uses the carry-aware step
        self._validate_input_ids(x)
        step = self._get_jitted("train_step_carry")
        pol = self.shape_policy
        pad_on = pol is not None and pol.enabled and self._pad_train_safe()
        if pad_on:
            # batch-axis bucketing (ragged epoch tails) before chunking;
            # time-axis chunk padding happens per-chunk below
            x, y, mask, label_mask = pol.pad_train_batch(
                x, y, mask, label_mask, path="tbptt")
        L = self.conf.tbptt_fwd_length
        T = x.shape[1]
        batch = x.shape[0]
        carries = self._init_carries(batch)
        # one device placement per BATCH, not per chunk (JX012: the
        # transfer belongs outside the loop); chunk slices below are
        # device-side views of these arrays
        x = _on_device(x)
        y = _on_device(y)
        mask = _on_device(mask)
        label_mask = _on_device(label_mask)
        from .layers.recurrent import Bidirectional
        # a backward-direction RNN would consume the padded timesteps FIRST,
        # polluting state that reaches every real timestep — never pad
        # bidirectional chunks
        pad_tail = pad_on and T % L != 0 and not any(
            isinstance(lc, Bidirectional) for lc in self.layers)
        traced = False
        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))
            xm = None if mask is None else mask[:, sl]
            ym = None if label_mask is None else label_mask[:, sl]
            yc = y[:, sl] if getattr(y, "ndim", 2) == 3 else y
            if pad_tail and sl.stop - sl.start < L:
                # final short chunk pads to the chunk length L so every
                # T hits the ONE compiled chunk program: padded timesteps
                # are zero in data AND feature mask, so the propagated
                # mask excludes them from the loss; this is the last
                # chunk, so the polluted carry is never consumed
                pad = L - (sl.stop - sl.start)
                xc_len = sl.stop - sl.start
                xm = xm if xm is not None else jnp.ones(
                    (batch, xc_len), jnp.float32)
                xm = _pad_time(xm, pad)
                if ym is not None and getattr(ym, "ndim", 1) == 2:
                    ym = _pad_time(ym, pad)
                xc = _pad_time(x[:, sl], pad)
                if getattr(yc, "ndim", 2) == 3:
                    yc = _pad_time(yc, pad)
                x_chunk = xc
            else:
                x_chunk = x[:, sl]
            (self.params, self.state, self.opt_state, self._rng, loss,
             gstats, carries) = step(
                self.params, self.state, self.opt_state, self._rng,
                x_chunk, yc, xm, ym, carries)
            traced = traced or step.last_call_traced
            # device scalar inside the chunk loop: a float() here would
            # host-sync every chunk, serializing tBPTT windows against
            # dispatch RTT; listeners reading get_score() materialize it
            self._score = loss
            self._last_grad_stats = gstats
            self.iteration += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, self.epoch)
        # one sync per batch, so deferred device failures surface in fit
        self._score = float(self._score)
        self._last_step_traced = traced

    def _init_carries(self, batch: int):
        """Zero carries for every recurrent layer (keyed ``layer_i``)."""
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        carries = {}
        for i, lc in enumerate(self.layers):
            if getattr(lc, "HAS_CARRY", False):
                carries[f"layer_{i}"] = lc.init_carry(batch, dtype)
        return carries

    # ------------------------------------------------------------- pretrain
    def pretrain(self, data, epochs: int = 1) -> "MultiLayerNetwork":
        """Greedy layerwise unsupervised pretraining (reference
        ``MultiLayerNetwork.pretrain(DataSetIterator)`` :1173): every
        PRETRAINABLE layer (AutoEncoder/RBM/VAE) trains on the features
        produced by the (already-pretrained) layers below it."""
        if self.params == {}:
            self.init()
        for i, lc in enumerate(self.layers):
            if getattr(lc, "PRETRAINABLE", False):
                self.pretrain_layer(i, data, epochs=epochs)
        return self

    def pretrain_layer(self, i: int, data, epochs: int = 1) -> None:
        """Pretrain one layer (reference ``pretrainLayer``).  The prefix
        0..i-1 runs inference-mode under the same jit; only layer i's params
        receive gradients/updates."""
        lc = self.layers[i]
        if not getattr(lc, "PRETRAINABLE", False):
            return
        if self.params == {}:
            self.init()
        from ._common import hyperparam_conf
        hc = hyperparam_conf(lc)
        updater = (hc.updater if hc is not None and hc.updater is not None
                   else self._default_updater())
        tx = updater.to_optax()
        lname = f"layer_{i}"
        opt = tx.init(self.params[lname])
        frozen = {k: v for k, v in self.params.items() if k != lname}
        # shared-cache entry: the step closes over conf/tx only; the frozen
        # prefix and running state ride as ARGUMENTS (the old closure baked
        # them in as trace constants AND re-jitted per pretrain_layer call)
        step = self._jit_cache.get(f"pretrain_{i}")
        if step is None:
            step = shared_jit(
                (type(self).__name__, self._topology_sig(), "pretrain", i),
                lambda: (_build_pretrain_step(self.conf, tx, i), (0, 1, 2)),
                name=f"pretrain_{i}")
            self._jit_cache[f"pretrain_{i}"] = step
        p_i = self.params[lname]
        if epochs > 1 and not hasattr(data, "shape") and \
                not isinstance(data, (tuple, list)) and \
                not hasattr(data, "features") and \
                not hasattr(data, "reset") and \
                hasattr(data, "__iter__") and iter(data) is data:
            # bare generator: materialize for re-iteration.  A list is always
            # a sequence of batches — only a TUPLE is a single (x, y) pair —
            # so a 2-element generator doesn't collapse into a pair below.
            data = list(data)
        for _ in range(epochs):
            for batch in self._pretrain_batches(data):
                # fused-RNG step: splits the key inside the program
                # (bit-identical to the host split it replaces) and
                # returns the successor; key + p_i + opt donate in place
                p_i, opt, self._rng, loss = step(
                    p_i, opt, self._rng, jnp.asarray(batch), frozen,
                    self.state)
                # device scalar in-loop (steps pipeline); one sync below
                self._score = loss
                self.iteration += 1
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration, self.epoch)
        # NOT exception-guarded: deferred device failures surface here
        self._score = float(self._score)
        self.params[lname] = p_i
        # rebuild optimizer state so supervised fine-tuning starts clean
        self.opt_state = self._tx.init(self.params)

    def _pretrain_batches(self, data):
        if hasattr(data, "shape"):                      # bare feature array
            yield data
            return
        if isinstance(data, tuple) and len(data) in (2, 4):
            yield self._normalize_batch(data)[0]        # (x, y): features only
            return
        if hasattr(data, "features"):                   # single DataSet
            yield self._normalize_batch(data)[0]
            return
        if hasattr(data, "reset"):
            data.reset()
        for b in data:
            yield b if hasattr(b, "shape") else self._normalize_batch(b)[0]

    def _fit_one(self, x, y, m, lm):
        """One train step (shared by fit's inner loop and fit_batch).

        Returns (and leaves in ``_score``) the still-ASYNC device loss
        scalar: the per-step ``float()`` here was the last unconditional
        host sync in the hot fit loop — it stalled the dispatch pipeline
        once per step for a value nothing reads until a listener or
        forensics flush asks (the lifetime audit's host-sync sweep).
        ``fit_batch``/``get_score`` materialize on demand; the fit loop
        materializes once at the end."""
        self._validate_input_ids(x)
        step_fn = self._get_jitted("train_step")
        pol = self.shape_policy
        if pol is not None and pol.enabled and self._pad_train_safe():
            # ragged batches (partial epoch tails) pad onto an
            # already-compiled bucket; padded rows are loss-masked so the
            # step is numerically the unpadded one (data/shapes.py)
            x, y, m, lm = pol.pad_train_batch(x, y, m, lm)
        prof = self._stepprof
        if prof is not None:
            _t = monotonic_s()
        x, y, m, lm = (_on_device(x), _on_device(y), _on_device(m),
                       _on_device(lm))
        if prof is not None:
            prof.mark("h2d", monotonic_s() - _t)
        # fused-RNG step: the key split happens inside the program and the
        # successor key comes back as an output (bit-identical sequence to
        # the host-side split this replaces; one less dispatch per step)
        (self.params, self.state, self.opt_state, self._rng, loss,
         gstats) = step_fn(
            self.params, self.state, self.opt_state, self._rng, x, y, m, lm)
        self._score = loss
        self._last_grad_stats = gstats
        self._last_step_traced = bool(getattr(step_fn, "last_call_traced",
                                              False))
        self.iteration += 1
        if prof is None:
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, self.epoch)
        else:
            _t = monotonic_s()
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, self.epoch)
            prof.mark("listener", monotonic_s() - _t)
        return self._score

    def fit_batch(self, batch) -> float:
        """One train step on one batch WITHOUT epoch bookkeeping (used by
        EarlyStoppingTrainer, which owns the epoch loop)."""
        if self.params == {}:
            self.init()
        return float(self._fit_one(*self._normalize_batch(batch)))

    # ------------------------------------------------------ stateful RNN API
    def rnn_time_step(self, x) -> Array:
        """Streaming inference with persistent recurrent state (reference
        ``rnnTimeStep``, MultiLayerNetwork.java:2690).  x: [b, t, f] or
        [b, f] (single step).  State persists across calls until
        ``rnn_clear_previous_state``."""
        from .layers.recurrent import Bidirectional
        if any(isinstance(lc, Bidirectional) for lc in self.layers):
            raise ValueError(
                "rnn_time_step does not support bidirectional layers — the "
                "backward pass needs the full sequence (reference throws "
                "likewise)")
        x = jnp.asarray(x)
        # [b, f] = one feature step, squeezed to [b,1,f] — EXCEPT for
        # embedding-sequence models, whose 2-D input is token ids [b, t]
        from .layers.feedforward import EmbeddingSequenceLayer
        ids_model = bool(self.layers) and isinstance(
            self.layers[0], EmbeddingSequenceLayer)
        squeeze = x.ndim == 2 and not ids_model
        if squeeze:
            x = x[:, None, :]
        if getattr(self, "_rnn_carries", None) is None or \
                self._rnn_carry_batch != x.shape[0]:
            self._rnn_carries = self._init_carries(x.shape[0])
            self._rnn_carry_batch = x.shape[0]
        fn = self._get_jitted("rnn_time_step")
        y, self._rnn_carries = fn(self.params, self.state, x, self._rnn_carries)
        return y[:, 0] if squeeze and y.ndim == 3 else y

    def rnn_clear_previous_state(self):
        self._rnn_carries = None
        self._rnn_carry_batch = -1

    def rnn_get_previous_state(self, layer: int):
        c = getattr(self, "_rnn_carries", None)
        return None if c is None else c.get(f"layer_{layer}")

    def rnn_set_previous_state(self, layer: int, state) -> None:
        if getattr(self, "_rnn_carries", None) is None:
            raise ValueError("no rnn state yet — call rnn_time_step first")
        self._rnn_carries[f"layer_{layer}"] = state

    @staticmethod
    def _normalize_batch(b):
        if isinstance(b, (tuple, list)):
            if len(b) == 2:
                return b[0], b[1], None, None
            if len(b) == 4:
                return tuple(b)
        if hasattr(b, "features"):
            return (b.features, b.labels,
                    getattr(b, "features_mask", None),
                    getattr(b, "labels_mask", None))
        raise ValueError(f"cannot interpret batch of type {type(b)}")

    # ------------------------------------------------------------- queries
    def get_score(self) -> float:
        # may be a device scalar mid-fit_on_device (kept async so epochs
        # pipeline); materialize on demand
        return float(self._score)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def param_bytes(self, per_device: bool = False) -> int:
        """Parameter memory: global bytes, or with ``per_device=True`` the
        bytes ONE device holds — a ZeRO-3 sharded net (``parallel/
        sharded.py`` NamedSharding layout) reports ~1/dp of global."""
        from ..parallel.sharded import param_bytes, per_device_param_bytes
        return per_device_param_bytes(self.params) if per_device \
            else param_bytes(self.params)

    def params_flat(self) -> np.ndarray:
        """Flat param vector — serialization/compat view, NOT a runtime
        invariant (see SURVEY §7 'hardest parts')."""
        leaves = []
        for i in range(len(self.layers)):
            lp = self.params.get(f"layer_{i}", {})
            for name in sorted(lp):
                leaves.append(np.asarray(lp[name]).reshape(-1))
        return np.concatenate(leaves) if leaves else np.zeros(0, np.float32)

    def evaluate(self, iterator_or_x, y=None):
        from ..evaluation.classification import Evaluation
        ev = Evaluation()
        for x, yy in self._eval_batches(iterator_or_x, y):
            ev.eval(np.asarray(yy), np.asarray(self.output(x)))
        return ev

    def evaluate_regression(self, iterator_or_x, y=None):
        from ..evaluation.regression import RegressionEvaluation
        ev = RegressionEvaluation()
        for x, yy in self._eval_batches(iterator_or_x, y):
            ev.eval(np.asarray(yy), np.asarray(self.output(x)))
        return ev

    def evaluate_roc(self, iterator_or_x, y=None, threshold_steps: int = 0):
        from ..evaluation.roc import ROC
        ev = ROC(threshold_steps)
        for x, yy in self._eval_batches(iterator_or_x, y):
            ev.eval(np.asarray(yy), np.asarray(self.output(x)))
        return ev

    def _eval_batches(self, it, y):
        if y is not None:
            yield it, y
            return
        if hasattr(it, "reset"):
            it.reset()
        for b in it:
            x, yy, _, _ = self._normalize_batch(b)
            yield x, yy

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def clone(self) -> "MultiLayerNetwork":
        import copy
        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        # REAL copies: the jitted train step donates the original's buffers
        # (donate_argnums), so aliasing them would leave the clone holding
        # deleted arrays after the original trains.
        copy_tree = lambda t: jax.tree_util.tree_map(lambda a: jnp.array(a), t)
        other.params = copy_tree(self.params)
        other.state = copy_tree(self.state)
        other._tx = other._build_tx()
        if self.opt_state is not None:
            other.opt_state = copy_tree(self.opt_state)
        else:
            other.init()
        # split the parent stream per clone: giving every replica the
        # conf-seed key would make data-parallel workers draw IDENTICAL
        # dropout masks/shuffles (correlated noise defeats the averaging)
        self._rng, other._rng = jax.random.split(self._rng)
        # deepcopied conf signs identically, so the clone's first step
        # reuses the parent's compiled executables from the shared cache
        other.shape_policy = self.shape_policy
        other.iteration = self.iteration
        other.epoch = self.epoch
        return other
