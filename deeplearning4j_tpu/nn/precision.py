"""First-class mixed-precision policy.

The reference runs CUDA fp32 end to end; the TPU-native fast path is
bf16 compute against f32 master weights (the MXU's native input type),
and fp16 needs loss scaling on top.  Instead of sprinkling ``.astype``
casts through user code (the TensorFlow-paper position: dtype decisions
belong in the SYSTEM — arxiv 1605.08695), the whole dtype story lives in
one conf-level object:

  - ``param_dtype``   master params + updater state (f32: the updater
    accumulates in full precision regardless of compute dtype)
  - ``compute_dtype`` forward/backward math (bf16 / f16)
  - ``keep_f32``      layer classes whose math stays f32 inside a
    low-precision stack (default: BatchNormalization — batch statistics
    are variance-of-mean reductions that cancel catastrophically in
    bf16); loss reductions and the fused softmax/log-softmax inside loss
    functions always run f32 (``nn/losses`` upcasts low-precision
    pre-activations at entry)
  - ``overrides``     per-layer dtype by layer NAME (``{"layer3":
    "float32"}`` pins one layer of an otherwise-bf16 stack)
  - ``loss_scale``    ``None`` | fixed float | ``"dynamic"``: the loss is
    multiplied by the scale inside the jitted step and gradients
    unscaled after ``value_and_grad``; non-finite gradients SKIP the
    update (params/updater/state unchanged) and halve the scale, while
    ``growth_interval`` consecutive finite steps double it — all traced
    into the step, zero extra dispatches.  fp16 defaults to dynamic.

The policy object lives in ``conf.defaults`` and therefore participates
in the compile-cache topology signature: an f32 and a bf16 variant of
the same stack can never false-share a trace, while two nets with equal
policies still share one compiled step.

Dynamic-scale state rides in the network ``state`` pytree under the
reserved ``"__precision__"`` key (a dict of three scalars), so it is
donated through the step, checkpointed, and restored like every other
piece of training state.

**Donation and the fused step** (PR 18): the scale/unscale/skip logic
is traced into the SAME program as the optimizer application and the
fused RNG succession, so the canonical train step's donation set —
params, state, updater state, and the RNG key (argnums ``(0, 1, 2,
3)``, AX007-maximal, floored by ``donation_min`` in
``tools/graftaudit/budgets.json``) — covers every buffer this policy
touches.  Two consequences worth keeping true: the unscaled-gradient
temporaries alias the donated master buffers rather than extending
peak-live, and the skip-update branch must keep returning the donated
params/state/updater values *positionally unchanged* — a skip that
rebuilt them as fresh outputs would silently break the alias match and
cost a full extra copy of the master weights every overflow step.

**Sharded masters** (ZeRO-3, ``parallel/sharded.py``): because the
masters are simply the param pytree, laying params out with a
``NamedSharding`` over the data axis makes them *sharded* masters with
no code here changing — the in-step per-layer cast produces the bf16
compute values (GSPMD may all-gather in bf16, halving the gather
bytes), gradients unscale/accumulate against the f32 shard, and the
updater applies its f32 update to the local shard only.  Tier-1 pins
this composition: a bf16 sharded run is bit-identical to the bf16
replicated run, and the masters never leave full precision
(``tests/test_sharded.py::test_sharded_masters_bf16_matches_replicated``).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..utils.serde import register_serde

#: reserved key in the network ``state`` pytree for loss-scale state
SCALE_STATE_KEY = "__precision__"

_ALIASES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "mixed_bfloat16": "bfloat16",
    "f16": "float16", "fp16": "float16", "float16": "float16",
    "mixed_float16": "float16",
    "f32": "float32", "fp32": "float32", "float32": "float32",
}


def _canon_dtype(dt: Optional[str]) -> Optional[str]:
    if dt is None:
        return None
    s = str(dt).lower()
    return _ALIASES.get(s, s)


@register_serde
@dataclass
class PrecisionPolicy:
    """Conf-level mixed-precision policy (see module docstring)."""
    compute_dtype: Optional[str] = None      # None/float32 = full precision
    param_dtype: str = "float32"
    loss_scale: Optional[Any] = None         # None | float | "dynamic"
    initial_scale: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 200
    keep_f32: Tuple[str, ...] = ("BatchNormalization",)
    overrides: Optional[Dict[str, str]] = None   # layer name -> dtype
    # KV-cache storage dtype for the paged generation cache (ROADMAP 2d):
    # None/float32 stores K/V as written; "int8" quantizes blocks at the
    # cache write (per-token, per-head absmax scale) and dequantizes at
    # the attention gather.  Lives on the policy — and therefore in the
    # compile-cache topology signature — so an int8-cache net and an f32
    # one can never false-share a trace.
    kv_dtype: Optional[str] = None

    def __post_init__(self):
        self.compute_dtype = _canon_dtype(self.compute_dtype)
        self.param_dtype = _canon_dtype(self.param_dtype) or "float32"
        if self.kv_dtype is not None:
            kd = str(self.kv_dtype).lower()
            kd = {"i8": "int8", "int8": "int8"}.get(kd, _canon_dtype(kd))
            if kd not in ("int8", "float32"):
                raise ValueError(
                    f"kv_dtype must be None, 'float32' or 'int8', got "
                    f"{self.kv_dtype!r}")
            self.kv_dtype = None if kd == "float32" else kd

    # ----------------------------------------------------------- queries
    @property
    def active(self) -> bool:
        return self.compute_dtype not in (None, "float32")

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == "dynamic"

    @property
    def scaled(self) -> bool:
        return self.loss_scale is not None

    def layer_dtype(self, lc) -> Optional[str]:
        """Compute dtype for one layer conf: per-name override, else f32
        for keep_f32 classes (wrappers resolved through
        ``hyperparam_conf``), else the stack compute dtype.  ``None`` when
        the policy is inactive."""
        if not self.active:
            return None
        name = getattr(lc, "name", None)
        if self.overrides and name in self.overrides:
            return _canon_dtype(self.overrides[name])
        from ._common import hyperparam_conf
        hc = hyperparam_conf(lc) or lc
        kinds = {type(hc).__name__, type(lc).__name__}
        if kinds & set(self.keep_f32):
            return "float32"
        return self.compute_dtype


def named_policy(name: str) -> PrecisionPolicy:
    """Policy from a shorthand string: ``'bfloat16'``/``'bf16'`` (no
    scaling), ``'float16'``/``'f16'``/``'mixed_float16'`` (dynamic
    scaling), ``'float32'`` (inactive)."""
    dt = _canon_dtype(name)
    if dt not in ("bfloat16", "float16", "float32"):
        raise ValueError(
            f"unknown precision '{name}' — use 'bfloat16', 'float16', "
            "'float32', or a PrecisionPolicy(...)")
    scale = "dynamic" if dt == "float16" else None
    return PrecisionPolicy(compute_dtype=None if dt == "float32" else dt,
                           loss_scale=scale)


def resolve(defaults: Dict[str, Any]) -> Optional[PrecisionPolicy]:
    """Resolved policy for a conf's ``defaults`` dict, or ``None`` for a
    full-precision net.  Back-compat: a bare ``compute_dtype`` string
    (the pre-policy knob) resolves to a plain bf16/f16 policy."""
    p = defaults.get("precision")
    if isinstance(p, str):
        p = named_policy(p)
    if p is None:
        cd = _canon_dtype(defaults.get("compute_dtype"))
        if cd and cd != "float32":
            p = PrecisionPolicy(compute_dtype=cd)
    if p is None or not p.active:
        return None
    if p.compute_dtype == "float16" and p.loss_scale is None:
        # fp16 without scaling underflows small gradients — dynamic is
        # the only safe default
        p = dataclasses.replace(p, loss_scale="dynamic")
    return p


def kv_cache_dtype(defaults: Dict[str, Any]) -> Optional[str]:
    """KV-cache storage dtype for a conf's ``defaults``: ``"int8"`` when
    the precision policy requests a quantized cache, else None (store as
    written).  Unlike :func:`resolve` this reads the policy even when
    compute runs full precision — an f32 net can still carry an int8
    cache (the cache is storage, not math)."""
    p = defaults.get("precision")
    if isinstance(p, str):
        p = named_policy(p)
    return getattr(p, "kv_dtype", None)


# ------------------------------------------------------------- step helpers
def init_scale_state(policy: Optional[PrecisionPolicy]):
    """Loss-scale carry for ``state[SCALE_STATE_KEY]`` (``None`` when the
    policy needs none).  Fixed-scale policies still carry the state so
    skip-step bookkeeping (``overflow_steps``) is observable."""
    if policy is None or not policy.scaled:
        return None
    import jax.numpy as jnp
    init = policy.initial_scale if policy.dynamic else float(policy.loss_scale)
    return {"scale": jnp.asarray(init, jnp.float32),
            "good_steps": jnp.asarray(0, jnp.int32),
            "overflow_steps": jnp.asarray(0, jnp.int32)}


def unscale_and_check(grads, scale):
    """Undo the loss scale on the gradient tree and report whether every
    leaf is finite — traced into the step.  Float leaves only
    (``_common.float_grad_leaves``): a ``SparseRows`` gradient carrier
    (``nn/sparse``) holds int32 row indices that must neither be scaled
    nor finiteness-checked."""
    import jax.numpy as jnp

    from ._common import float_grad_leaves, map_float_grads
    inv = 1.0 / scale
    grads = map_float_grads(lambda g: g * inv, grads)
    checks = [jnp.all(jnp.isfinite(g)) for g in float_grad_leaves(grads)]
    finite = jnp.stack(checks).all() if checks else jnp.asarray(True)
    return grads, finite


def overflow_skip(policy: PrecisionPolicy, ls: Dict[str, Any], finite,
                  params, new_params, opt_state, new_opt, state, new_state,
                  gstats):
    """Non-finite grads SKIP the step wholesale: params, updater state and
    layer state all keep their pre-step values, the scale backs off, the
    overflow counter ticks — all where-selected inside the one traced
    program (zero extra dispatches).  Returns the selected
    ``(new_params, new_opt, new_state, sel)``; callers with extra
    per-step outputs (tBPTT carries) reuse ``sel`` on them."""
    import jax
    import jax.numpy as jnp

    def sel(new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(finite, a, b), new, old)

    new_params = sel(new_params, params)
    new_opt = sel(new_opt, opt_state)
    old_layers = {k: v for k, v in state.items() if k != SCALE_STATE_KEY}
    new_layers = {k: v for k, v in new_state.items()
                  if k != SCALE_STATE_KEY}
    new_state = sel(new_layers, old_layers)
    new_state[SCALE_STATE_KEY] = next_scale_state(policy, ls, finite)
    gstats["loss_scale"] = ls["scale"]
    # pin the counter dtype: a weak-int where() is i64 under x64, i32
    # without — listeners should see one output signature everywhere
    gstats["overflow"] = jnp.where(finite, 0, 1).astype(jnp.int32)
    return new_params, new_opt, new_state, sel


def next_scale_state(policy: PrecisionPolicy, ls: Dict[str, Any], finite):
    """Traced update of the loss-scale carry after one step whose
    gradients were ``finite`` (a traced bool scalar)."""
    import jax.numpy as jnp
    scale, good = ls["scale"], ls["good_steps"]
    overflow = ls["overflow_steps"] + jnp.where(finite, 0, 1).astype(
        jnp.int32)
    if not policy.dynamic:
        return {"scale": scale, "good_steps": good,
                "overflow_steps": overflow}
    good = jnp.where(finite, good + 1, 0).astype(jnp.int32)
    grow = finite & (good >= policy.growth_interval)
    scale = jnp.where(
        grow, scale * policy.growth_factor,
        jnp.where(finite, scale, scale * policy.backoff_factor))
    # never scale below 1 (pointless) or above f32 range
    scale = jnp.clip(scale, 1.0, 2.0 ** 60)
    good = jnp.where(grow, 0, good).astype(jnp.int32)
    return {"scale": scale, "good_steps": good, "overflow_steps": overflow}
