"""Mixture-of-experts layer for the config DSL.

No reference equivalent (pre-transformer era) — the layer-level face of
``parallel/expert.py``: top-1 Switch routing over a stack of expert FFNs,
fixed capacity for static shapes.  The load-balancing aux loss is threaded
through layer *state* (``aux_loss``) and added to the objective by the
network loss (AUX_LOSS flag) — state-threading keeps it remat/checkpoint
safe.  Works on FF [b, f] and RNN [b, t, f] inputs; for expert-parallel
sharding see parallel/expert.py's shard_map formulation with all-to-all.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import BaseLayerConf

__all__ = ["MixtureOfExpertsLayer"]


@register_serde
@dataclass
class MixtureOfExpertsLayer(BaseLayerConf):
    """params: router [f, E], w1 [E, f, hidden], b1, w2 [E, hidden, n_out],
    b2.  capacity_factor sizes each expert's token budget as
    ``capacity_factor * tokens / n_experts``."""
    INPUT_KIND = "any"   # FF [b,f] and RNN [b,t,f] both handled natively
    AUX_LOSS = True

    n_in: int = 0
    n_out: int = 0
    n_experts: int = 4
    hidden: int = 0                 # defaults to 4 * n_in
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size if itype.kind in ("ff", "rnn") else \
                itype.flat_size()

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == "rnn":
            return InputType.recurrent(self.n_out, itype.timesteps)
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(
                f"layer '{self.name}': n_in/n_out unset — declare the "
                "network input type")
        h = self.hidden or 4 * self.n_in
        kr, k1, k2 = jax.random.split(key, 3)
        params = {
            "router": self.make_weight(kr, (self.n_in, self.n_experts)),
            "w1": self.make_weight(k1, (self.n_experts, self.n_in, h)),
            "b1": self.make_bias((self.n_experts, 1, h)),
            "w2": self.make_weight(k2, (self.n_experts, h, self.n_out)),
            "b2": self.make_bias((self.n_experts, 1, self.n_out)),
        }
        return {"params": params,
                "state": {"aux_loss": jnp.zeros((), self._dtype())}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        from ...parallel.expert import moe_ffn
        params = variables["params"]
        x = self.maybe_dropout_input(key, x, train)
        if x.ndim == 4:   # CNN [b,h,w,c] -> flat [b, h*w*c] (set_n_in used
            x = x.reshape(x.shape[0], -1)  # flat_size for cnn input types)
        shape = x.shape
        x2d = x.reshape(-1, shape[-1])
        t = x2d.shape[0]
        capacity = max(int(self.capacity_factor * t / self.n_experts), 1)
        y, aux = moe_ffn(params, x2d, capacity, act=self.act_fn)
        new_state = {"aux_loss": (self.aux_loss_weight * aux).astype(
            jnp.result_type(x))}
        return y.reshape(shape[:-1] + (self.n_out,)), new_state
