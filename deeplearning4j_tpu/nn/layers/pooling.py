"""Global pooling (reference ``nn/layers/pooling/GlobalPoolingLayer.java``).

Pools CNN activations [b, h, w, c] -> [b, c] or RNN activations
[b, t, f] -> [b, f], with mask-aware reductions for variable-length time
series (reference ``util/MaskedReductionUtil.java``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import LayerConf


@register_serde
@dataclass
class GlobalPoolingLayer(LayerConf):
    pooling_type: str = "max"    # max | avg | sum | pnorm
    pnorm: int = 2
    collapse_dimensions: bool = True

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == "cnn":
            return InputType.feed_forward(itype.channels)
        if itype.kind == "rnn":
            return InputType.feed_forward(itype.size)
        if itype.kind == "cnn3d":
            return InputType.feed_forward(itype.channels)
        raise ValueError(f"global pooling over {itype.kind} input")

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        if x.ndim == 4:
            axes = (1, 2)
        elif x.ndim == 3:
            axes = (1,)
        elif x.ndim == 5:
            axes = (1, 2, 3)
        else:
            raise ValueError(f"global pooling needs 3/4/5-d input, got {x.ndim}d")
        pt = self.pooling_type.lower()

        if mask is not None and x.ndim == 3:
            # masked time reduction (reference MaskedReductionUtil)
            m = mask.astype(x.dtype)
            while m.ndim < x.ndim:
                m = m[..., None]
            if pt == "max":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=axes)
            elif pt == "sum":
                y = jnp.sum(x * m, axis=axes)
            elif pt == "avg":
                y = jnp.sum(x * m, axis=axes) / jnp.maximum(
                    jnp.sum(m, axis=axes), 1e-8)
            elif pt == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum(jnp.abs(x * m) ** p, axis=axes) ** (1.0 / p)
            else:
                raise ValueError(f"unknown pooling type '{self.pooling_type}'")
            return y, variables.get("state", {})

        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt == "avg":
            y = jnp.mean(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(f"unknown pooling type '{self.pooling_type}'")
        return y, variables.get("state", {})

    def feed_forward_mask(self, mask, itype):
        return None  # time dimension is gone after global pooling
