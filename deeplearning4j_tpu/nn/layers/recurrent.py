"""Recurrent layers: SimpleRnn, LSTM, GravesLSTM, bidirectional wrappers.

Reference: ``nn/layers/recurrent/LSTMHelpers.java:58`` (shared fwd :68-/bwd
:392- math, IFOG gate order, peepholes via axpy :235-236,260,303),
``GravesLSTM.java:46``, ``GravesBidirectionalLSTM.java`` (fwd+bwd outputs
ADDed, :224), ``nn/conf/layers/{LSTM,GravesLSTM,GravesBidirectionalLSTM}``.

TPU-native design: one ``lax.scan`` over the time axis per layer — XLA compiles
the cell into a single fused step program (the cuDNN-LSTM-helper role), with
the input projection ``x @ W`` hoisted OUT of the scan as one big [b*t, 4h]
matmul that tiles onto the MXU.  State (h, c) is an explicit functional carry:

    init_carry(batch)                         -> carry
    scan(params, x, carry, mask)              -> (y [b,t,h], final_carry)

``apply`` runs with a zero carry (reference fit() semantics: no cross-batch
state).  Truncated-BPTT chunk state and ``rnnTimeStep`` streaming inference
(reference MultiLayerNetwork.java:2690 stateMap) thread the carry explicitly
through MultiLayerNetwork.

Masking: for padded step t with mask 0, output is zeroed and the carry holds
its previous value (reference variable-length semantics).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...utils.serde import register_serde
from .. import activations as _act
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf
from .feedforward import OutputLayer


@dataclass
class BaseRecurrentLayer(BaseLayerConf):
    """Common recurrent contract (reference ``nn/api/layers/RecurrentLayer``).
    HAS_CARRY marks layers with streaming/tBPTT state (h, c); RnnOutputLayer
    reuses the shape plumbing but is stateless."""
    INPUT_KIND = "rnn"
    HAS_CARRY = False

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind != "rnn":
                raise ValueError(
                    f"layer '{self.name}': recurrent layer expects RNN input, got {itype}")
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    # -- carry protocol ------------------------------------------------------
    def init_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def scan(self, params, x, carry, mask=None):
        """x: [b, t, f] -> (y [b, t, h], final_carry)."""
        raise NotImplementedError

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        params = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        carry = self.init_carry(x.shape[0], x.dtype)
        y, _ = self.scan(params, x, carry, mask)
        return y, variables.get("state", {})

    def apply_with_carry(self, variables, x, carry, *, train=False, key=None,
                         mask=None):
        params = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        if carry is None:
            carry = self.init_carry(x.shape[0], x.dtype)
        y, new_carry = self.scan(params, x, carry, mask)
        return y, new_carry

    @staticmethod
    def _mask_step(m_t, h_new, h_prev, y_t):
        """Masked step: carry holds, output zeroed."""
        if m_t is None:
            return h_new, y_t
        m = m_t[:, None]
        return m * h_new + (1 - m) * h_prev, y_t * m


def _time_major(x):
    return jnp.swapaxes(x, 0, 1)


@register_serde
@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} U + b)
    (reference ``nn/conf/layers/recurrent/SimpleRnn``)."""
    HAS_CARRY = True

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(f"layer '{self.name}': n_in/n_out unset")
        k1, k2 = jax.random.split(key)
        return {"params": {
            "W": self.make_weight(k1, (self.n_in, self.n_out)),
            "U": self.make_weight(k2, (self.n_out, self.n_out)),
            "b": self.make_bias((self.n_out,)),
        }, "state": {}}

    def init_carry(self, batch, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def scan(self, params, x, carry, mask=None):
        act = self.act_fn
        xz = x.astype(params["W"].dtype) @ params["W"] + params["b"]  # [b,t,h]
        xz_t = _time_major(xz)
        m_t = None if mask is None else _time_major(mask.astype(xz.dtype))

        def step(c, inp):
            xzt, mt = inp
            h_new = act(xzt + c["h"] @ params["U"])
            h, y = self._mask_step(mt, h_new, c["h"], h_new)
            return {"h": h}, y

        if m_t is None:
            def step_nm(c, xzt):
                h_new = act(xzt + c["h"] @ params["U"])
                return {"h": h_new}, h_new
            final, ys = lax.scan(step_nm, carry, xz_t)
        else:
            final, ys = lax.scan(step, carry, (xz_t, m_t))
        return _time_major(ys), final


@register_serde
@dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM, no peepholes (reference ``nn/conf/layers/LSTM`` — the
    cuDNN-compatible variant).  Gate order IFOG as in LSTMHelpers."""
    HAS_CARRY = True
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    # optional accelerator fast path (the reference's reflective cuDNN
    # helper hook, ConvolutionLayer.java:74-84 pattern): "pallas" fuses the
    # recurrence into one kernel with U resident in VMEM; silently falls
    # back to lax.scan when unsupported (mask, peepholes, exotic
    # activations) — CudnnLSTMHelper.checkSupported semantics.
    helper: Optional[str] = None

    _PEEPHOLES = False

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(f"layer '{self.name}': n_in/n_out unset")
        k1, k2, k3 = jax.random.split(key, 3)
        h = self.n_out
        # biases at bias_init, forget-gate slice [h:2h] OVERWRITTEN with
        # forget_gate_bias_init (reference LSTMParamInitializer order)
        b = jnp.full((4 * h,), self.resolved("bias_init", 0.0), self._dtype())
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        params = {
            "W": self.make_weight(k1, (self.n_in, 4 * h)),
            "U": self.make_weight(k2, (h, 4 * h)),
            "b": b,
        }
        if self._PEEPHOLES:
            params["p"] = jnp.zeros((3 * h,), self._dtype())  # pi, pf, po
        return {"params": params, "state": {}}

    def init_carry(self, batch, dtype=jnp.float32):
        h = self.n_out
        return {"h": jnp.zeros((batch, h), dtype), "c": jnp.zeros((batch, h), dtype)}

    def scan(self, params, x, carry, mask=None):
        if self.helper == "pallas":
            from ...ops import pallas_lstm
            if pallas_lstm.supports(
                    peepholes=self._PEEPHOLES,
                    gate_activation=self.gate_activation,
                    activation=self.resolved("activation", "tanh"),
                    masked=mask is not None):
                ys, hT, cT = pallas_lstm.lstm_forward_fast(
                    x.astype(jnp.float32),
                    params["W"].astype(jnp.float32),
                    params["U"].astype(jnp.float32),
                    params["b"].astype(jnp.float32),
                    carry["h"].astype(jnp.float32),
                    carry["c"].astype(jnp.float32))
                return ys, {"h": hT, "c": cT}
        h_units = self.n_out
        act = self.act_fn
        gate = _act.get(self.gate_activation)
        # hoist the input projection: one [b*t, 4h] MXU matmul
        xz = x.astype(params["W"].dtype) @ params["W"] + params["b"]
        xz_t = _time_major(xz)
        m_t = None if mask is None else _time_major(mask.astype(xz.dtype))
        peep = params.get("p") if self._PEEPHOLES else None

        def cell(c, xzt, mt):
            z = xzt + c["h"] @ params["U"]
            zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
            if peep is not None:
                pi, pf, po = jnp.split(peep, 3)
                zi = zi + pi * c["c"]
                zf = zf + pf * c["c"]
            i = gate(zi)
            f = gate(zf)
            g = act(zg)
            c_new = f * c["c"] + i * g
            if peep is not None:
                zo = zo + po * c_new
            o = gate(zo)
            h_new = o * act(c_new)
            if mt is None:
                return {"h": h_new, "c": c_new}, h_new
            m = mt[:, None]
            return ({"h": m * h_new + (1 - m) * c["h"],
                     "c": m * c_new + (1 - m) * c["c"]}, h_new * m)

        if m_t is None:
            final, ys = lax.scan(lambda c, xzt: cell(c, xzt, None), carry, xz_t)
        else:
            final, ys = lax.scan(lambda c, inp: cell(c, *inp), carry, (xz_t, m_t))
        return _time_major(ys), final


@register_serde
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference ``GravesLSTM.java:46``,
    peephole math LSTMHelpers.java:235-236,260,303)."""
    _PEEPHOLES = True


@register_serde
@dataclass
class Bidirectional(LayerConf):
    """Bidirectional wrapper (reference ``nn/conf/layers/recurrent/Bidirectional``):
    runs the wrapped recurrent layer forwards and (a separate copy) backwards
    over time, combining with mode add/mul/average/concat."""
    fwd: Optional[BaseRecurrentLayer] = None
    mode: str = "concat"           # concat | add | mul | average

    def __post_init__(self):
        if self.fwd is not None and self.name is None:
            self.name = f"bi_{self.fwd.name or type(self.fwd).__name__}"

    # delegate config resolution to the wrapped layer
    def has_params(self):
        return True

    def apply_global_defaults(self, defaults):
        self.fwd.apply_global_defaults(defaults)

    def set_n_in(self, itype, override=False):
        self.fwd.set_n_in(itype, override)

    def output_type(self, itype: InputType) -> InputType:
        inner = self.fwd.output_type(itype)
        if self.mode == "concat":
            return InputType.recurrent(inner.size * 2, inner.timesteps)
        return inner

    def regularization_score(self, params):
        return (self.fwd.regularization_score(params.get("fwd", {})) +
                self.fwd.regularization_score(params.get("bwd", {})))

    def init(self, key, itype):
        k1, k2 = jax.random.split(key)
        vf = self.fwd.init(k1, itype)
        vb = self.fwd.init(k2, itype)
        return {"params": {"fwd": vf["params"], "bwd": vb["params"]},
                "state": {}}

    def _combine(self, yf, yb):
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        if self.mode == "add":
            return yf + yb
        if self.mode == "mul":
            return yf * yb
        if self.mode == "average":
            return 0.5 * (yf + yb)
        raise ValueError(f"unknown bidirectional mode '{self.mode}'")

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = variables["params"]
        kf, kb = (jax.random.split(key) if key is not None else (None, None))
        yf, _ = self.fwd.apply({"params": p["fwd"], "state": {}}, x,
                               train=train, key=kf, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.fwd.apply({"params": p["bwd"], "state": {}}, xr,
                               train=train, key=kb, mask=mr)
        yb = jnp.flip(yb, axis=1)
        return self._combine(yf, yb), variables.get("state", {})


@register_serde
@dataclass
class GravesBidirectionalLSTM(Bidirectional):
    """Convenience: bidirectional GravesLSTM combined by ADD
    (reference ``GravesBidirectionalLSTM.java:224`` fwdOutput.add(backOutput))."""
    n_in: int = 0
    n_out: int = 0
    mode: str = "add"

    def __post_init__(self):
        if self.fwd is None:
            self.fwd = GravesLSTM(n_in=self.n_in, n_out=self.n_out,
                                  name=f"{self.name or 'gbilstm'}_inner")
        super().__post_init__()

    def set_n_in(self, itype, override=False):
        super().set_n_in(itype, override)
        self.n_in = self.fwd.n_in


@register_serde
@dataclass
class RnnOutputLayer(OutputLayer):
    """Time-distributed dense + loss (reference ``nn/conf/layers/RnnOutputLayer``).
    Input [b, t, f] -> output [b, t, n_out]; label mask [b, t] supported.
    Reuses OutputLayer's head (the matmul is rank-agnostic); only the shape
    contract differs."""
    INPUT_KIND = "rnn"

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind != "rnn":
                raise ValueError(
                    f"layer '{self.name}': RnnOutputLayer expects RNN input, got {itype}")
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)


@register_serde
@dataclass
class LastTimeStep(LayerConf):
    """Wrapper: keep only the last (mask-aware) time step of a recurrent
    layer's output → FF (reference ``recurrent/LastTimeStep`` /
    ``LastTimeStepVertex``)."""
    underlying: Optional[LayerConf] = None

    @property
    def HAS_CARRY(self):  # delegate streaming/tBPTT state to the wrapped layer
        return getattr(self.underlying, "HAS_CARRY", False)

    def init_carry(self, batch, dtype=jnp.float32):
        return self.underlying.init_carry(batch, dtype)

    def apply_with_carry(self, variables, x, carry, *, train=False, key=None,
                         mask=None):
        y, new_carry = self.underlying.apply_with_carry(
            variables, x, carry, train=train, key=key, mask=mask)
        if mask is not None:
            # last NONZERO index (not count-1): robust to non-contiguous masks,
            # matching LastTimeStepVertex semantics
            idx = (mask.shape[1] - 1 -
                   jnp.argmax(mask[:, ::-1] > 0, axis=1)).astype(jnp.int32)
            out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0]
        else:
            out = y[:, -1]
        return out, new_carry

    def has_params(self):
        return self.underlying.has_params()

    def apply_global_defaults(self, defaults):
        if hasattr(self.underlying, "apply_global_defaults"):
            self.underlying.apply_global_defaults(defaults)

    def set_n_in(self, itype, override=False):
        self.underlying.set_n_in(itype, override)

    def output_type(self, itype: InputType) -> InputType:
        inner = self.underlying.output_type(itype)
        return InputType.feed_forward(inner.size)

    def init(self, key, itype):
        return self.underlying.init(key, itype)

    def regularization_score(self, params):
        return self.underlying.regularization_score(params)

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        y, state = self.underlying.apply(variables, x, train=train, key=key,
                                         mask=mask)
        if mask is not None:
            # last unmasked step per example
            # last NONZERO index (not count-1): robust to non-contiguous masks,
            # matching LastTimeStepVertex semantics
            idx = (mask.shape[1] - 1 -
                   jnp.argmax(mask[:, ::-1] > 0, axis=1)).astype(jnp.int32)
            out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0]
        else:
            out = y[:, -1]
        return out, state

    def feed_forward_mask(self, mask, itype):
        return None
