"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference: ``nn/layers/normalization/BatchNormalization.java:41`` (+ cuDNN
helper hook :55-65) and ``LocalResponseNormalization.java``.

TPU-native: batch statistics are plain jnp reductions XLA fuses into the
surrounding program (the cuDNN helper tier is unnecessary); running mean/var
live in the layer's ``state`` pytree and are updated functionally — the new
state is returned from ``apply`` and threaded by the network, replacing the
reference's in-place global-stats mutation.  Under data parallelism the batch
axis is sharded, so XLA computes *cross-replica* batch stats automatically
when the reduction spans the mesh — sync batch-norm for free.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf


@jax.custom_vjp
def _bn_train_norm(x, gamma, beta, eps):
    """Training-mode batch norm with a hand-derived backward.

    The autodiff-derived VJP spreads the input gradient over several reduce
    fusions; this version pins the backward to the two-pass minimum (one
    multi-output reduce for dbeta/dgamma, one elementwise pass for dx) —
    the role the reference delegates to
    ``CudnnBatchNormalizationHelper.java:45`` (cudnnBatchNormalizationBackward
    is the same fused formula).  Returns (y, mean, var) with stats in f32.

    INVARIANT: the backward rule drops the cotangents on the returned
    mean/var — they exist only to feed the NON-differentiated running-stats
    EMA.  Do not differentiate through a consumer of these outputs (e.g. a
    batch-statistics regularizer); the gradient would be silently missing
    that contribution.
    """
    y, mean, var, _ = _bn_fwd_math(x, gamma, beta, eps)
    return y, mean, var


def _acc_dtype(dt):
    """f32 accumulation for low-precision inputs, f64 stays f64 (the
    gradient-check oracle runs the whole net in double)."""
    return jnp.promote_types(dt, jnp.float32)


def _bn_stats(x, eps):
    """One-pass f32 statistics: (mean, var, inv).  Shared by the XLA path
    and the Pallas helper (ops/pallas_bn) — one copy of the E[x²]−E[x]²
    form and its var>=0 clamp."""
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(_acc_dtype(x.dtype))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
    return mean, var, lax.rsqrt(var + eps)


def _bn_fwd_math(x, gamma, beta, eps):
    mean, var, inv = _bn_stats(x, eps)
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    y = xhat * gamma + beta
    return y, mean, var, inv


def _bn_train_fwd(x, gamma, beta, eps):
    y, mean, var, inv = _bn_fwd_math(x, gamma, beta, eps)
    return (y, mean, var), (x, gamma, mean, inv)


def _bn_bwd_math(x, gamma, mean, inv, dy):
    """The hand-derived two-pass backward: (dx, dgamma, dbeta).  Shared by
    the XLA path and the Pallas helper — one copy of the f32-accumulation
    and cast subtleties."""
    axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    acc = _acc_dtype(x.dtype)
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dyf = dy.astype(acc)
    # pass 1: both reductions share one read of (dy, xhat)
    dbeta = jnp.sum(dyf, axis=axes)
    dgamma = jnp.sum(dyf * xhat.astype(acc), axis=axes)
    # pass 2: dx = inv*gamma*(dy - dbeta/n - xhat*dgamma/n)
    coef = (inv * gamma.astype(acc)).astype(x.dtype)
    dx = coef * (dy - (dbeta / n).astype(x.dtype)
                 - xhat * (dgamma / n).astype(x.dtype))
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


def _bn_train_bwd(res, cts):
    x, gamma, mean, inv = res
    # mean/var cotangents dropped by contract — see _bn_train_norm docstring
    dy, _, _ = cts
    return _bn_bwd_math(x, gamma, mean, inv, dy) + (None,)  # eps nondiff, None


_bn_train_norm.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_serde
@dataclass
class BatchNormalization(BaseLayerConf):
    """Batch norm over the channel/feature axis (NHWC: reduce N,H,W).

    state: mean, var (running estimates, reference "global" stats).
    params: gamma, beta (unless lock_gamma_beta).
    decay matches the reference's exponential moving average semantics
    (``BatchNormalization.java`` decay default 0.9).
    """
    INPUT_KIND = "any"  # works on ff [b,f] and cnn [b,h,w,c]

    n_out: int = 0               # feature/channel count (inferred)
    decay: float = 0.9
    eps: float = 1e-5
    is_minibatch: bool = True
    lock_gamma_beta: bool = False
    gamma_init: float = 1.0
    beta_init: float = 0.0
    # optional Pallas fused apply+activation (the CudnnBatchNormalization-
    # Helper selection pattern); falls back to the XLA path when the kernel
    # doesn't support the config.  Measured neutral-to-negative on ResNet50
    # (XLA's own fusions already cover the chain — BENCH_NOTES round 3).
    helper: Optional[str] = None

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_out == 0 or override:
            self.n_out = itype.channels if itype.kind == "cnn" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return itype

    def init(self, key, itype):
        if self.n_out <= 0:
            raise ValueError(
                f"layer '{self.name}': feature count unknown — declare input type")
        f = self.n_out
        dt = self._dtype()
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((f,), self.gamma_init, dt),
                      "beta": jnp.full((f,), self.beta_init, dt)}
        state = {"mean": jnp.zeros((f,), dt), "var": jnp.ones((f,), dt)}
        return {"params": params, "state": state}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        params, state = variables["params"], variables["state"]
        if train and self.is_minibatch:
            # One-pass f32 statistics (E[x²]−E[x]², single HBM read) and a
            # hand-derived two-pass backward — see _bn_train_norm.
            if self.lock_gamma_beta:
                gamma = jnp.ones((x.shape[-1],), x.dtype)
                beta = jnp.zeros((x.shape[-1],), x.dtype)
            else:
                gamma, beta = params["gamma"], params["beta"]
            y = None
            if self.helper == "pallas":
                from ...ops import pallas_bn
                act_name = self.resolved("activation", "identity")
                backend = jax.default_backend()
                if (backend in ("tpu", "cpu")   # no Triton path wired here
                        and pallas_bn.supports(activation=act_name,
                                               shape=x.shape,
                                               itemsize=x.dtype.itemsize)):
                    y, mean, var = pallas_bn.bn_act_train(
                        x, gamma.astype(x.dtype), beta.astype(x.dtype),
                        self.eps, act_name, backend == "cpu")
                    # activation already fused in the kernel
            if y is None:
                y, mean, var = _bn_train_norm(x, gamma.astype(x.dtype),
                                              beta.astype(x.dtype), self.eps)
                y = self.act_fn(y)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * mean.astype(
                             state["mean"].dtype),
                         "var": d * state["var"] + (1 - d) * var.astype(
                             state["var"].dtype)}
            return y, new_state
        mean, var = state["mean"], state["var"]
        xhat = (x - mean.astype(x.dtype)) * lax.rsqrt(
            var.astype(x.dtype) + self.eps)
        if not self.lock_gamma_beta:
            xhat = xhat * params["gamma"] + params["beta"]
        return self.act_fn(xhat), state


@register_serde
@dataclass
class LocalResponseNormalization(LayerConf):
    """Across-channel LRN (reference
    ``nn/layers/normalization/LocalResponseNormalization.java``):
    y = x / (k + alpha * sum_{j in window} x_j^2)^beta, window of n channels.
    """
    INPUT_KIND = "cnn"

    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75
    n: int = 5

    def output_type(self, itype: InputType) -> InputType:
        return itype

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        half = self.n // 2
        sq = x * x
        # channel-window running sum via reduce_window on the minor axis
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, self.n),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, self.n - 1 - half)))
        y = x / jnp.power(self.k + self.alpha * summed, self.beta)
        return y, variables.get("state", {})
