"""Object detection — YOLOv2 output layer.

Reference ``nn/layers/objdetect/Yolo2OutputLayer.java:67`` + conf
``nn/conf/layers/objdetect/Yolo2OutputLayer``.  NHWC layout (TPU-native;
the reference is NCHW):

  network activations  [b, H, W, B*(5+C)]   per box: (tx, ty, tw, th, tconf)
  labels               [b, H, W, 4+C]       (x1, y1, x2, y2) in GRID units
                                            + one-hot class; all-zero class
                                            vector ⇒ no object in that cell

Loss (YOLOv2): responsible predictor = best-IoU box per object cell
(selected under stop_gradient); position/size L2 on (sigmoid(xy)+cell,
sqrt(wh)); confidence targets IoU for responsible boxes, 0 elsewhere
(λ_noobj weighted); softmax cross-entropy over classes.  Everything is
branch-free masking — jit/TPU friendly.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import LayerConf

Array = jax.Array


@register_serde
@dataclass
class Yolo2OutputLayer(LayerConf):
    """YOLOv2 detection head: no params, shapes the loss over conv features."""
    boxes: List[List[float]] = field(default_factory=lambda: [[1.0, 1.0]])
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    INPUT_KIND = "cnn"
    # the YOLO loss sums over the whole grid and IGNORES the mask argument,
    # so shape-bucketing must never pad batches through this head
    # (data/shapes.py gates on this flag)
    SUPPORTS_LOSS_MASK = False

    # ---- shape ----
    def output_type(self, itype: InputType) -> InputType:
        return itype

    def has_params(self):
        return False

    def n_boxes(self):
        return len(self.boxes)

    def n_classes(self, channels: int) -> int:
        return channels // self.n_boxes() - 5

    def _split(self, x):
        """[b,H,W,B*(5+C)] → xy [b,H,W,B,2], wh, conf [b,H,W,B], cls [b,H,W,B,C]."""
        b, H, W, ch = x.shape
        B = self.n_boxes()
        C = self.n_classes(ch)
        x = x.reshape(b, H, W, B, 5 + C)
        return x[..., 0:2], x[..., 2:4], x[..., 4], x[..., 5:]

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        """Activated predictions: sigmoid(xy)+cell offset, priors*exp(wh),
        sigmoid(conf), softmax(classes) — [b,H,W,B,5+C] in grid units."""
        txy, twh, tconf, tcls = self._split(x)
        b, H, W, B = tconf.shape
        cell = self._cell_offsets(H, W, x.dtype)
        priors = jnp.asarray(self.boxes, x.dtype)
        xy = jax.nn.sigmoid(txy) + cell[None, :, :, None, :]
        wh = priors[None, None, None, :, :] * jnp.exp(jnp.clip(twh, -10, 10))
        conf = jax.nn.sigmoid(tconf)
        cls = jax.nn.softmax(tcls, axis=-1)
        out = jnp.concatenate(
            [xy, wh, conf[..., None], cls], axis=-1)
        return out, variables.get("state", {})

    @staticmethod
    def _cell_offsets(H, W, dtype):
        gy, gx = jnp.meshgrid(jnp.arange(H, dtype=dtype),
                              jnp.arange(W, dtype=dtype), indexing="ij")
        return jnp.stack([gx, gy], axis=-1)  # [H,W,2] (x,y)

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        txy, twh, tconf, tcls = self._split(x)
        b, H, W, B = tconf.shape
        dtype = x.dtype
        cell = self._cell_offsets(H, W, dtype)
        priors = jnp.asarray(self.boxes, dtype)

        # predictions in grid units
        pred_xy = jax.nn.sigmoid(txy) + cell[None, :, :, None, :]
        pred_wh = priors[None, None, None, :, :] * jnp.exp(
            jnp.clip(twh, -10, 10))
        pred_conf = jax.nn.sigmoid(tconf)

        # ground truth
        gt_x1y1 = labels[..., 0:2]
        gt_x2y2 = labels[..., 2:4]
        gt_cls = labels[..., 4:]
        obj = (jnp.sum(gt_cls, axis=-1) > 0).astype(dtype)      # [b,H,W]
        gt_xy = 0.5 * (gt_x1y1 + gt_x2y2)
        gt_wh = jnp.maximum(gt_x2y2 - gt_x1y1, 1e-6)

        # IoU of each predictor box vs the cell's gt box  [b,H,W,B]
        iou = self._iou(pred_xy, pred_wh, gt_xy[..., None, :],
                        gt_wh[..., None, :])
        best = jax.lax.stop_gradient(
            jax.nn.one_hot(jnp.argmax(iou, axis=-1), B, dtype=dtype))
        resp = best * obj[..., None]                            # [b,H,W,B]

        # position/size loss on the responsible predictor
        d_xy = jnp.sum((pred_xy - gt_xy[..., None, :]) ** 2, axis=-1)
        d_wh = jnp.sum((jnp.sqrt(pred_wh) -
                        jnp.sqrt(gt_wh[..., None, :])) ** 2, axis=-1)
        loss_coord = jnp.sum(resp * (d_xy + d_wh))

        # confidence: responsible → target IoU; others → 0 with λ_noobj
        conf_tgt = jax.lax.stop_gradient(iou)
        loss_conf = jnp.sum(resp * (pred_conf - conf_tgt) ** 2) + \
            self.lambda_no_obj * jnp.sum((1 - resp) * pred_conf ** 2)

        # class probabilities: softmax xent at object cells
        logp = jax.nn.log_softmax(tcls, axis=-1)
        cls_xent = -jnp.sum(gt_cls[..., None, :] * logp, axis=-1)  # [b,H,W,B]
        loss_cls = jnp.sum(resp * cls_xent)

        total = self.lambda_coord * loss_coord + loss_conf + loss_cls
        return total / b if average else total

    @staticmethod
    def _iou(xy1, wh1, xy2, wh2):
        min1, max1 = xy1 - wh1 / 2, xy1 + wh1 / 2
        min2, max2 = xy2 - wh2 / 2, xy2 + wh2 / 2
        inter = jnp.prod(jnp.clip(jnp.minimum(max1, max2) -
                                  jnp.maximum(min1, min2), 0.0, None), axis=-1)
        a1 = jnp.prod(wh1, axis=-1)
        a2 = jnp.prod(wh2, axis=-1)
        return inter / (a1 + a2 - inter + 1e-9)


def get_predicted_objects(activated, threshold: float = 0.5):
    """Decode [b,H,W,B,5+C] activated predictions into per-image detections
    (reference ``YoloUtils.getPredictedObjects``): list over batch of
    (x1, y1, x2, y2, confidence, class_index) arrays in grid units."""
    import numpy as np
    acts = np.asarray(activated)
    out = []
    for img in acts:
        dets = []
        H, W, B, _ = img.shape
        for r in range(H):
            for c in range(W):
                for bi in range(B):
                    p = img[r, c, bi]
                    conf = p[4]
                    if conf >= threshold:
                        cx, cy, w, h = p[0], p[1], p[2], p[3]
                        cls = int(np.argmax(p[5:]))
                        dets.append((cx - w / 2, cy - h / 2,
                                     cx + w / 2, cy + h / 2,
                                     float(conf * p[5 + cls]), cls))
        out.append(np.asarray(dets, dtype=np.float32).reshape(-1, 6))
    return out


def non_max_suppression(detections, iou_threshold: float = 0.45):
    """Greedy per-class NMS over one image's [n, 6] detections
    (x1, y1, x2, y2, score, class) — reference ``YoloUtils.nms``.
    Returns the surviving rows, score-descending."""
    import numpy as np
    dets = np.asarray(detections, np.float32).reshape(-1, 6)
    if len(dets) == 0:
        return dets
    keep = []
    for cls in np.unique(dets[:, 5]):
        d = dets[dets[:, 5] == cls]
        d = d[np.argsort(-d[:, 4])]
        while len(d):
            best = d[0]
            keep.append(best)
            if len(d) == 1:
                break
            rest = d[1:]
            ix1 = np.maximum(best[0], rest[:, 0])
            iy1 = np.maximum(best[1], rest[:, 1])
            ix2 = np.minimum(best[2], rest[:, 2])
            iy2 = np.minimum(best[3], rest[:, 3])
            inter = (np.clip(ix2 - ix1, 0, None)
                     * np.clip(iy2 - iy1, 0, None))
            a1 = (best[2] - best[0]) * (best[3] - best[1])
            a2 = ((rest[:, 2] - rest[:, 0])
                  * (rest[:, 3] - rest[:, 1]))
            iou = inter / np.maximum(a1 + a2 - inter, 1e-9)
            d = rest[iou < iou_threshold]
    out = np.asarray(keep, np.float32).reshape(-1, 6)
    return out[np.argsort(-out[:, 4])]
