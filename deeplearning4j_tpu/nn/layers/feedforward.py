"""Feed-forward layers: Dense, Output, Loss, Activation, Dropout, Embedding.

Reference: ``nn/layers/feedforward/dense/DenseLayer.java``,
``nn/conf/layers/{DenseLayer,OutputLayer,LossLayer,ActivationLayer,
DropoutLayer,EmbeddingLayer}``.  The matmul runs in the layer's dtype
(bfloat16-ready) and XLA fuses bias+activation into it — the MXU path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .. import losses as _losses
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf


@register_serde
@dataclass
class DenseLayer(BaseLayerConf):
    INPUT_KIND = "ff"

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    # ---- shape inference ----------------------------------------------------
    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind not in ("ff", "cnnflat"):
                raise ValueError(
                    f"layer '{self.name}': dense layer expects FF input, got {itype}")
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    # ---- runtime ------------------------------------------------------------
    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(
                f"layer '{self.name}': n_in={self.n_in}, n_out={self.n_out} — "
                "set n_in explicitly or declare the network input type "
                "(set_input_type) so it can be inferred")
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def pre_output(self, variables, x, *, train=False, key=None):
        params = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        z = self.pre_output(variables, x, train=train, key=key)
        return self.act_fn(z), variables.get("state", {})


@register_serde
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference ``nn/conf/layers/OutputLayer``).
    ``loss_weights`` is the reference's per-output weight vector
    (e.g. ``LossMCXENT(weights)`` for class imbalance): the per-unit loss
    is scaled column-wise before reduction."""
    loss: str = "mcxent"
    loss_weights: Optional[Sequence[float]] = None

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        z = self.pre_output(variables, x, train=train, key=key)
        act = self.resolved("activation", "identity")
        if self.loss_weights is not None:
            w = jnp.asarray(self.loss_weights, z.dtype)
            if w.shape[-1] != self.n_out:
                raise ValueError(
                    f"layer '{self.name}': {w.shape[-1]} loss weights for "
                    f"{self.n_out} outputs")
            return _losses.get(self.loss)(labels, z, act, mask,
                                          unit_weights=w)
        return _losses.get(self.loss)(labels, z, act, mask)


@register_serde
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference
    ``nn/layers/training/CenterLossOutputLayer.java`` / conf
    ``CenterLossOutputLayer``): intra-class compactness term
    λ/2·||f − c_y||².  Centers live as a param whose gradient is decoupled
    from the feature gradient via stop_gradient — the α-rate moving-average
    center update of the reference becomes plain SGD on the center term."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, itype):
        out = super().init(key, itype)
        out["params"]["centers"] = jnp.zeros((self.n_out, self.n_in),
                                             self._dtype())
        return out

    def regularization_score(self, params):
        # centers are statistics, not weights — exclude from l1/l2
        return super().regularization_score(
            {k: v for k, v in params.items() if k != "centers"})

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        base = super().compute_loss(variables, x, labels, train=train,
                                    key=key, mask=mask, average=average)
        centers = variables["params"]["centers"]
        c_sel = labels @ centers                     # one-hot row-select
        diff_f = x - jax.lax.stop_gradient(c_sel)    # pulls features to centers
        diff_c = jax.lax.stop_gradient(x) - c_sel    # pulls centers to features
        per_f = jnp.sum(diff_f ** 2, axis=-1)
        per_c = jnp.sum(diff_c ** 2, axis=-1)
        if mask is not None:
            w = mask.reshape(mask.shape[0], -1)[:, 0]  # per-example weight
            denom = jnp.maximum(jnp.sum(w), 1.0)
            mean_f = jnp.sum(w * per_f) / denom
            mean_c = jnp.sum(w * per_c) / denom
        else:
            mean_f, mean_c = jnp.mean(per_f), jnp.mean(per_c)
        l_feat = 0.5 * self.lambda_ * mean_f
        l_cent = 0.5 * self.alpha * mean_c
        # value-neutral center update: contributes gradient (to centers only)
        # but zero to the reported score — matching the reference, where the
        # α-rate center update happens outside the loss
        return base + l_feat + l_cent - jax.lax.stop_gradient(l_cent)


@register_serde
@dataclass
class LossLayer(BaseLayerConf):
    """Loss-only head, no params (reference ``nn/conf/layers/LossLayer``)."""
    loss: str = "mse"

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        act = self.resolved("activation", "identity")
        return _losses.get(self.loss)(labels, x, act, mask)


@register_serde
@dataclass
class ActivationLayer(BaseLayerConf):
    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})


@register_serde
@dataclass
class DropoutLayer(BaseLayerConf):
    """Standalone dropout (reference ``nn/conf/layers/DropoutLayer``)."""

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.maybe_dropout_input(key, self.act_fn(x), train), \
            variables.get("state", {})


@register_serde
@dataclass
class EmbeddingLayer(BaseLayerConf):
    """Index → vector lookup (reference ``nn/conf/layers/EmbeddingLayer``).

    Input: integer indices [batch] or one-hot [batch, n_in]; output
    [batch, n_out].  Lookup is a gather — on TPU this stays on-device and
    differentiates to a scatter-add, replacing the reference's row-view
    update trick.
    """
    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        params = variables["params"]
        if x.ndim == 2 and x.shape[-1] == self.n_in and self.n_in > 1:
            idx = jnp.argmax(x, axis=-1)  # one-hot input
        else:
            idx = x.reshape(x.shape[0]).astype(jnp.int32)
        z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self.act_fn(z), variables.get("state", {})


@register_serde
@dataclass
class EmbeddingSequenceLayer(BaseLayerConf):
    """Token-id sequence → embedding sequence: [b, t] int (or one-hot
    [b, t, n_in]) → [b, t, n_out] (reference ``EmbeddingSequenceLayer``).
    Gather on device; backward is a scatter-add."""
    INPUT_KIND = "rnn"

    n_in: int = 0     # vocabulary size
    n_out: int = 0    # embedding dim

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def init(self, key, itype):
        return {"params": {"W": self.make_weight(key,
                                                 (self.n_in, self.n_out))},
                "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        W = variables["params"]["W"]
        if x.ndim == 3:           # one-hot [b, t, v]: matmul keeps the MXU
            z = x.astype(W.dtype) @ W
        else:
            z = W[x.astype(jnp.int32)]
        return self.act_fn(z), variables.get("state", {})
