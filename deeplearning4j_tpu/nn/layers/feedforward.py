"""Feed-forward layers: Dense, Output, Loss, Activation, Dropout, Embedding.

Reference: ``nn/layers/feedforward/dense/DenseLayer.java``,
``nn/conf/layers/{DenseLayer,OutputLayer,LossLayer,ActivationLayer,
DropoutLayer,EmbeddingLayer}``.  The matmul runs in the layer's dtype
(bfloat16-ready) and XLA fuses bias+activation into it — the MXU path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .. import losses as _losses
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf


@register_serde
@dataclass
class DenseLayer(BaseLayerConf):
    INPUT_KIND = "ff"

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    # ---- shape inference ----------------------------------------------------
    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind not in ("ff", "cnnflat"):
                raise ValueError(
                    f"layer '{self.name}': dense layer expects FF input, got {itype}")
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    # ---- runtime ------------------------------------------------------------
    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(
                f"layer '{self.name}': n_in={self.n_in}, n_out={self.n_out} — "
                "set n_in explicitly or declare the network input type "
                "(set_input_type) so it can be inferred")
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def pre_output(self, variables, x, *, train=False, key=None):
        params = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        z = self.pre_output(variables, x, train=train, key=key)
        return self.act_fn(z), variables.get("state", {})


@register_serde
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference ``nn/conf/layers/OutputLayer``).
    ``loss_weights`` is the reference's per-output weight vector
    (e.g. ``LossMCXENT(weights)`` for class imbalance): the per-unit loss
    is scaled column-wise before reduction."""
    loss: str = "mcxent"
    loss_weights: Optional[Sequence[float]] = None

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        z = self.pre_output(variables, x, train=train, key=key)
        act = self.resolved("activation", "identity")
        if self.loss_weights is not None:
            w = jnp.asarray(self.loss_weights, z.dtype)
            if w.shape[-1] != self.n_out:
                raise ValueError(
                    f"layer '{self.name}': {w.shape[-1]} loss weights for "
                    f"{self.n_out} outputs")
            return _losses.get(self.loss)(labels, z, act, mask,
                                          unit_weights=w)
        return _losses.get(self.loss)(labels, z, act, mask)


@register_serde
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (reference
    ``nn/layers/training/CenterLossOutputLayer.java`` / conf
    ``CenterLossOutputLayer``): intra-class compactness term
    λ/2·||f − c_y||².  Centers live as a param whose gradient is decoupled
    from the feature gradient via stop_gradient — the α-rate moving-average
    center update of the reference becomes plain SGD on the center term."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, itype):
        out = super().init(key, itype)
        out["params"]["centers"] = jnp.zeros((self.n_out, self.n_in),
                                             self._dtype())
        return out

    def regularization_score(self, params):
        # centers are statistics, not weights — exclude from l1/l2
        return super().regularization_score(
            {k: v for k, v in params.items() if k != "centers"})

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        base = super().compute_loss(variables, x, labels, train=train,
                                    key=key, mask=mask, average=average)
        centers = variables["params"]["centers"]
        c_sel = labels @ centers                     # one-hot row-select
        diff_f = x - jax.lax.stop_gradient(c_sel)    # pulls features to centers
        diff_c = jax.lax.stop_gradient(x) - c_sel    # pulls centers to features
        per_f = jnp.sum(diff_f ** 2, axis=-1)
        per_c = jnp.sum(diff_c ** 2, axis=-1)
        if mask is not None:
            w = mask.reshape(mask.shape[0], -1)[:, 0]  # per-example weight
            denom = jnp.maximum(jnp.sum(w), 1.0)
            mean_f = jnp.sum(w * per_f) / denom
            mean_c = jnp.sum(w * per_c) / denom
        else:
            mean_f, mean_c = jnp.mean(per_f), jnp.mean(per_c)
        l_feat = 0.5 * self.lambda_ * mean_f
        l_cent = 0.5 * self.alpha * mean_c
        # value-neutral center update: contributes gradient (to centers only)
        # but zero to the reported score — matching the reference, where the
        # α-rate center update happens outside the loss
        return base + l_feat + l_cent - jax.lax.stop_gradient(l_cent)


@register_serde
@dataclass
class LossLayer(BaseLayerConf):
    """Loss-only head, no params (reference ``nn/conf/layers/LossLayer``)."""
    loss: str = "mse"

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        act = self.resolved("activation", "identity")
        return _losses.get(self.loss)(labels, x, act, mask)


@register_serde
@dataclass
class ActivationLayer(BaseLayerConf):
    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})


@register_serde
@dataclass
class DropoutLayer(BaseLayerConf):
    """Standalone dropout (reference ``nn/conf/layers/DropoutLayer``)."""

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.maybe_dropout_input(key, self.act_fn(x), train), \
            variables.get("state", {})


def _embedding_invalid(msg: str):
    """Raise the serving stack's client-error type (a bad id batch is a
    caller bug, distinguishable from model-internal ValueErrors — the
    generation engine's InvalidInputError pattern)."""
    from ...parallel.inference import InvalidInputError
    raise InvalidInputError(msg)


def _validate_id_dtype(x, name: str, n_in: int):
    if not jnp.issubdtype(x.dtype, jnp.integer):
        _embedding_invalid(
            f"layer '{name}': embedding ids must be an integer dtype, got "
            f"{x.dtype} — a float id batch would silently truncate; pass "
            f"int ids, or a one-hot batch with trailing dim {n_in}")


def _validate_id_range(idx, name: str, n_in: int):
    """Concrete (host-visible) id batches are range-checked up front;
    traced ids are validated by the caller before dispatch (a traced
    gather clamps, so an in-program check could only corrupt silently)."""
    if isinstance(idx, jax.core.Tracer):
        return
    lo = int(jnp.min(idx)) if idx.size else 0
    hi = int(jnp.max(idx)) if idx.size else 0
    if lo < 0 or hi >= n_in:
        _embedding_invalid(
            f"layer '{name}': embedding ids out of range [{lo}, {hi}] for "
            f"vocabulary of {n_in} — the on-device gather would clamp "
            "silently")


def validate_host_ids(lc, x) -> None:
    """Boundary (host-side) id-range validation for embedding-first
    networks.  fit/output/score TRACE the forward, where a range check
    cannot run (the traced gather clamps silently), so the network
    entry points validate the concrete batch BEFORE dispatch — the
    generation engine's validate-at-admission pattern.  Device-resident
    batches (a ``DevicePrefetchIterator`` upstream) skip: materializing
    them here would stall the pipeline overlap, and their producers
    validated host-side.  Float/one-hot batches skip too — the dtype
    contract is static and already raises at trace time."""
    if x is None or isinstance(x, (list, tuple)) or \
            isinstance(x, jax.core.Tracer) or isinstance(x, jax.Array):
        return
    import numpy as np
    arr = np.asarray(x)
    if arr.ndim == 0 or arr.size == 0 or \
            not np.issubdtype(arr.dtype, np.integer):
        return
    lo, hi = int(arr.min()), int(arr.max())
    if lo < 0 or hi >= lc.n_in:
        _embedding_invalid(
            f"layer '{lc.name}': embedding ids out of range [{lo}, {hi}] "
            f"for vocabulary of {lc.n_in} — the on-device gather would "
            "clamp silently")


@register_serde
@dataclass
class EmbeddingLayer(BaseLayerConf):
    """Index → vector lookup (reference ``nn/conf/layers/EmbeddingLayer``).

    Input: integer indices [batch] / [batch, 1], or one-hot
    [batch, n_in]; output [batch, n_out].  Lookup is a gather — on TPU
    this stays on-device and differentiates to a scatter-add, replacing
    the reference's row-view update trick.

    ``sparse_grad=True`` opts the table into the densified sparse
    gradient path (``nn/sparse``): the train step exchanges coalesced
    touched-row index+value blocks instead of the dense ``[n_in,
    n_out]`` cotangent, and the updater touches only those rows (lazy
    row-sparse semantics — exact for stateless updaters; stateful
    mirrors skip untouched-row decay).  ``sparse_grad_capacity`` pads
    the per-step block to a fixed size (None = exact bound); a capacity
    below the bound is refused at trace time.  Requires the layer to be
    first in the stack (ids come straight from the batch) and no
    l1/l2 on the table (dense decay touches every row).
    """
    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True
    sparse_grad: bool = False
    sparse_grad_capacity: Optional[int] = None

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def decode_ids(self, x):
        """Id view of one input batch: [batch] int32 ids, or None for a
        one-hot batch.  Validates the id path's dtype (float ids used
        to truncate silently via astype) and, for concrete batches, the
        id range."""
        if x.ndim == 2 and x.shape[-1] == self.n_in and self.n_in > 1 and \
                not jnp.issubdtype(x.dtype, jnp.integer):
            return None                      # one-hot input
        if x.ndim == 2 and x.shape[-1] == 1:
            x = x[:, 0]                      # [b, 1] id column
        if x.ndim != 1:
            # integer [b, n_in] with n_in > 1 is the historical int
            # one-hot form — decode it like the float one-hot path
            if x.ndim == 2 and x.shape[-1] == self.n_in and self.n_in > 1:
                return None
            _embedding_invalid(
                f"layer '{self.name}': expected ids [batch]/[batch, 1] or "
                f"one-hot [batch, {self.n_in}], got shape {tuple(x.shape)}")
        _validate_id_dtype(x, self.name, self.n_in)
        idx = x.astype(jnp.int32)
        _validate_id_range(idx, self.name, self.n_in)
        return idx

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        params = variables["params"]
        idx = self.decode_ids(x)
        if idx is None:
            idx = jnp.argmax(x, axis=-1)     # one-hot input
        if self.sparse_grad:
            from .. import sparse as _sparse
            z = _sparse.embedding_lookup(params["W"], idx)
        else:
            z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self.act_fn(z), variables.get("state", {})


@register_serde
@dataclass
class EmbeddingSequenceLayer(BaseLayerConf):
    """Token-id sequence → embedding sequence: [b, t] int (or one-hot
    [b, t, n_in]) → [b, t, n_out] (reference ``EmbeddingSequenceLayer``).
    Gather on device; backward is a scatter-add.

    An exactly-one-hot-shaped [b, t, n_in] input decodes to ids
    (argmax) and rides the same gather — the historical
    ``x @ W`` matmul is O(b·t·n_in·n_out) dense MXU work (ruinous under
    a bf16 policy at real vocab sizes) for what is a lookup.  Callers
    that feed SOFT distributions over the vocabulary (expected
    embeddings, a semantic the matmul computes and argmax does not) opt
    back in with ``one_hot_matmul=True``.

    ``sparse_grad`` / ``sparse_grad_capacity``: see
    :class:`EmbeddingLayer` — same densified-gradient contract over the
    [b, t] id path.
    """
    INPUT_KIND = "rnn"

    n_in: int = 0     # vocabulary size
    n_out: int = 0    # embedding dim
    one_hot_matmul: bool = False
    sparse_grad: bool = False
    sparse_grad_capacity: Optional[int] = None

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def init(self, key, itype):
        return {"params": {"W": self.make_weight(key,
                                                 (self.n_in, self.n_out))},
                "state": {}}

    def decode_ids(self, x):
        """Id view of one input batch: [b, t] int32 ids, or None when
        the batch must ride the one-hot matmul (``one_hot_matmul=True``,
        or a 3-D input that is not one-hot-shaped)."""
        if x.ndim == 3:
            if self.n_in > 0 and x.shape[-1] != self.n_in:
                # a stale tokenizer / vocab-size mismatch would otherwise
                # surface as a cryptic dot_general shape error deep in
                # the trace
                _embedding_invalid(
                    f"layer '{self.name}': 3-D input has trailing dim "
                    f"{x.shape[-1]} but the vocabulary is {self.n_in} — "
                    f"expected one-hot [batch, time, {self.n_in}] (or "
                    "integer ids [batch, time])")
            if self.one_hot_matmul or self.n_in <= 0:
                return None
            return jnp.argmax(x, axis=-1)
        if x.ndim != 2:
            _embedding_invalid(
                f"layer '{self.name}': expected ids [batch, time] or "
                f"one-hot [batch, time, {self.n_in}], got shape "
                f"{tuple(x.shape)}")
        _validate_id_dtype(x, self.name, self.n_in)
        idx = x.astype(jnp.int32)
        _validate_id_range(idx, self.name, self.n_in)
        return idx

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        W = variables["params"]["W"]
        idx = self.decode_ids(x)
        if idx is None:           # explicit opt-in (soft distributions)
            z = x.astype(W.dtype) @ W
        elif self.sparse_grad:
            from .. import sparse as _sparse
            z = _sparse.embedding_lookup(W, idx)
        else:
            z = W[idx]
        return self.act_fn(z), variables.get("state", {})
