"""Feed-forward layers: Dense, Output, Loss, Activation, Dropout, Embedding.

Reference: ``nn/layers/feedforward/dense/DenseLayer.java``,
``nn/conf/layers/{DenseLayer,OutputLayer,LossLayer,ActivationLayer,
DropoutLayer,EmbeddingLayer}``.  The matmul runs in the layer's dtype
(bfloat16-ready) and XLA fuses bias+activation into it — the MXU path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .. import losses as _losses
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf


@register_serde
@dataclass
class DenseLayer(BaseLayerConf):
    INPUT_KIND = "ff"

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    # ---- shape inference ----------------------------------------------------
    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind not in ("ff", "cnnflat"):
                raise ValueError(
                    f"layer '{self.name}': dense layer expects FF input, got {itype}")
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    # ---- runtime ------------------------------------------------------------
    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(
                f"layer '{self.name}': n_in={self.n_in}, n_out={self.n_out} — "
                "set n_in explicitly or declare the network input type "
                "(set_input_type) so it can be inferred")
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def pre_output(self, variables, x, *, train=False, key=None):
        params = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        z = self.pre_output(variables, x, train=train, key=key)
        return self.act_fn(z), variables.get("state", {})


@register_serde
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (reference ``nn/conf/layers/OutputLayer``)."""
    loss: str = "mcxent"

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        z = self.pre_output(variables, x, train=train, key=key)
        act = self.resolved("activation", "identity")
        return _losses.get(self.loss)(labels, z, act, mask)


@register_serde
@dataclass
class LossLayer(BaseLayerConf):
    """Loss-only head, no params (reference ``nn/conf/layers/LossLayer``)."""
    loss: str = "mse"

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None, average=True):
        act = self.resolved("activation", "identity")
        return _losses.get(self.loss)(labels, x, act, mask)


@register_serde
@dataclass
class ActivationLayer(BaseLayerConf):
    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.act_fn(x), variables.get("state", {})


@register_serde
@dataclass
class DropoutLayer(BaseLayerConf):
    """Standalone dropout (reference ``nn/conf/layers/DropoutLayer``)."""

    def has_params(self):
        return False

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return self.maybe_dropout_input(key, self.act_fn(x), train), \
            variables.get("state", {})


@register_serde
@dataclass
class EmbeddingLayer(BaseLayerConf):
    """Index → vector lookup (reference ``nn/conf/layers/EmbeddingLayer``).

    Input: integer indices [batch] or one-hot [batch, n_in]; output
    [batch, n_out].  Lookup is a gather — on TPU this stays on-device and
    differentiates to a scatter-add, replacing the reference's row-view
    update trick.
    """
    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        params = {"W": self.make_weight(key, (self.n_in, self.n_out))}
        if self.has_bias:
            params["b"] = self.make_bias((self.n_out,))
        return {"params": params, "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        params = variables["params"]
        if x.ndim == 2 and x.shape[-1] == self.n_in and self.n_in > 1:
            idx = jnp.argmax(x, axis=-1)  # one-hot input
        else:
            idx = x.reshape(x.shape[0]).astype(jnp.int32)
        z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self.act_fn(z), variables.get("state", {})
