"""Layer base classes.

The reference splits layer *configuration* (``nn/conf/layers/``) from layer
*implementation* (``nn/layers/``), with params held as views into one flat
array (``nn/api/Layer.java:38``, ``nn/params/DefaultParamInitializer``).  The
TPU-native design collapses the two: a layer IS a serializable config dataclass
with two pure functions —

    init(key, input_type)  -> {"params": {...}, "state": {...}}
    apply(variables, x, *, train, key, mask, state) -> (y, new_state)

Params live in a pytree (XLA manages placement/donation — the flat view's job),
``state`` carries non-trained arrays (batch-norm running stats, reference
``nn/layers/normalization/BatchNormalization.java`` global mean/var).  All
``apply`` bodies are jit-traceable: no data-dependent Python control flow.

Common hyperparameters mirror the reference's ``BaseLayer`` config: activation,
weight_init (+distribution), l1/l2 (weights and bias separately), per-layer
updater override, dropout, weight noise, constraints.  ``None`` means "inherit
the network-level default" (resolved by the network builder, as DL4J's
``NeuralNetConfiguration.Builder`` does).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import activations as _act
from ..conf.constraints import LayerConstraint
from ..conf.distribution import Distribution
from ..conf.dropout import IDropout, IWeightNoise, resolve as _resolve_dropout
from ..conf.input_type import InputType
from ..conf.updaters import UpdaterConf
from ..weights import init_weights

Array = jax.Array
Variables = Dict[str, Dict[str, Array]]

# Global-default-able fields and their fallback values (mirrors
# NeuralNetConfiguration.Builder's defaults applied to each layer).
INHERITED_DEFAULTS = {
    "activation": "identity",
    "weight_init": "xavier",
    "weight_dist": None,
    "bias_init": 0.0,
    "l1": 0.0,
    "l2": 0.0,
    "l1_bias": 0.0,
    "l2_bias": 0.0,
    "updater": None,
    "bias_updater": None,
    "dropout": None,
    "weight_noise": None,
    "constraints": None,
    "dtype": "float32",
    "gradient_normalization": None,
    "gradient_normalization_threshold": 1.0,
}


@dataclass
class LayerConf:
    """Root of the layer-config hierarchy (reference ``nn/conf/layers/Layer``)."""
    name: Optional[str] = None

    # ---- to be overridden ---------------------------------------------------
    def output_type(self, itype: InputType) -> InputType:
        return itype

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        """Infer input size from the previous layer's output type."""

    def init(self, key: jax.Array, itype: InputType) -> Variables:
        return {"params": {}, "state": {}}

    def apply(self, variables: Variables, x: Array, *, train: bool = False,
              key: Optional[jax.Array] = None, mask: Optional[Array] = None
              ) -> Tuple[Array, Dict[str, Array]]:
        raise NotImplementedError

    # ---- generic helpers ----------------------------------------------------
    def has_params(self) -> bool:
        return False

    def n_params(self, itype: InputType) -> int:
        sizes = 0
        v = self.init(jax.random.PRNGKey(0), itype)
        for p in jax.tree_util.tree_leaves(v.get("params", {})):
            sizes += p.size
        return sizes

    def regularization_score(self, params: Dict[str, Array]) -> Array:
        # f32 scalar, not dtype-defaulted: zeros(()) is f64 under x64 and
        # would promote the whole loss (graftaudit AX001)
        return jnp.zeros((), jnp.float32)

    def feed_forward_mask(self, mask: Optional[Array], itype: InputType
                          ) -> Optional[Array]:
        """Propagate a mask through this layer (reference Layer.java:282)."""
        return mask


@dataclass
class BaseLayerConf(LayerConf):
    """Layers with weights (reference ``nn/conf/layers/BaseLayer``)."""
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    weight_dist: Optional[Distribution] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    updater: Optional[UpdaterConf] = None
    bias_updater: Optional[UpdaterConf] = None
    dropout: Optional[Any] = None          # float retain-prob or IDropout
    weight_noise: Optional[IWeightNoise] = None
    constraints: Optional[List[LayerConstraint]] = None
    dtype: Optional[str] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    _BIAS_PARAMS = ("b", "gamma", "beta", "mean", "var")  # bias-like (no l2 by default)

    # ---- defaults resolution -----------------------------------------------
    def apply_global_defaults(self, defaults: Dict[str, Any]) -> None:
        """Fill None fields from network-level defaults (builder semantics)."""
        my_fields = {f.name for f in dataclasses.fields(self)}
        for k, fallback in INHERITED_DEFAULTS.items():
            if k not in my_fields:
                continue
            if getattr(self, k, None) is None:
                setattr(self, k, defaults.get(k, fallback))

    def resolved(self, name, fallback=None):
        v = getattr(self, name, None)
        if v is None:
            v = INHERITED_DEFAULTS.get(name, fallback)
        if v is None:
            v = fallback
        return v

    # ---- helpers ------------------------------------------------------------
    def has_params(self) -> bool:
        return True

    @property
    def act_fn(self):
        return _act.get(self.resolved("activation", "identity"))

    def _dtype(self):
        return jnp.dtype(self.resolved("dtype", "float32"))

    def make_weight(self, key, shape):
        return init_weights(key, shape, self.resolved("weight_init", "xavier"),
                            self.weight_dist, self._dtype())

    def make_bias(self, shape):
        return jnp.full(shape, self.resolved("bias_init", 0.0), self._dtype())

    def maybe_dropout_input(self, key, x, train: bool):
        """Reference semantics: dropout is applied to the layer *input*."""
        d = _resolve_dropout(self.dropout)
        if train and d is not None and key is not None:
            return d.apply(key, x)
        return x

    def maybe_noise_weights(self, key, params: Dict[str, Array], train: bool):
        wn = self.weight_noise
        if train and wn is not None and key is not None:
            out = dict(params)
            for i, (k, v) in enumerate(sorted(params.items())):
                if k not in self._BIAS_PARAMS:
                    out[k] = wn.apply(jax.random.fold_in(key, i), v)
            return out
        return params

    def regularization_score(self, params: Dict[str, Array]) -> Array:
        l1 = float(self.resolved("l1", 0.0) or 0.0)
        l2 = float(self.resolved("l2", 0.0) or 0.0)
        l1b = float(self.resolved("l1_bias", 0.0) or 0.0)
        l2b = float(self.resolved("l2_bias", 0.0) or 0.0)
        score = jnp.zeros((), jnp.float32)
        for k, v in params.items():
            is_bias = k in self._BIAS_PARAMS
            a1, a2 = (l1b, l2b) if is_bias else (l1, l2)
            if a1:
                score = score + a1 * jnp.sum(jnp.abs(v))
            if a2:
                score = score + 0.5 * a2 * jnp.sum(v * v)
        return score


def split_key(key, n):
    if key is None:
        return [None] * n
    return list(jax.random.split(key, n))
