"""Attention layer family: LayerNorm, MultiHeadAttention, TransformerBlock.

No counterpart in the reference (pre-transformer, SURVEY.md §5) — this is the
long-context capability the TPU build adds as first-class.  The layers follow
the same config-dataclass contract as every other layer
(``nn/layers/base.py``), so they compose with MultiLayerNetwork /
ComputationGraph, serde, transfer learning, and the zoo.

Attention impl tiers (select with ``attn_impl``):
  'reference' — jnp SDPA (``ops.attention.sdpa_reference``), always correct.
  'flash'     — pallas tiled kernel (``ops.flash_attention``), O(t) memory.
  'ring'      — ring attention over the mesh 'seq' axis (inside shard_map).
  'ulysses'   — all-to-all sequence parallelism (inside shard_map).
  'auto'      — selects by the measured crossover: reference below
                ``DEFAULT_FLASH_MIN_SEQ`` tokens (or a masked input),
                flash at/above it — the ``CudnnAlgoMode`` role.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import BaseLayerConf, LayerConf


@register_serde
@dataclass
class LayerNormLayer(BaseLayerConf):
    """Layer normalization over the feature axis (gamma/beta learned)."""
    n_out: int = 0
    eps: float = 1e-5

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_out == 0 or override:
            self.n_out = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return itype

    def init(self, key, itype):
        return {"params": {"gamma": jnp.ones((self.n_out,), self._dtype()),
                           "beta": jnp.zeros((self.n_out,), self._dtype())},
                "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = variables["params"]
        y = _layer_norm(x, p["gamma"], p["beta"], self.eps)
        return y, variables.get("state", {})


def _layer_norm(x, gamma, beta, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * gamma + beta


_ATTN_IMPLS = ("auto", "reference", "flash", "ring", "ulysses")

# Measured crossover on the TPU v5e chip (BENCH_NOTES.md "transformer
# campaign"): with the r3 128x128 kernel blocks, reference SDPA won the
# full train step up to s=2048; with the swept block sizes
# (ops/flash_attention._auto_blocks) flash wins at EVERY kernel-supported
# length — full-model step ms flash/ref: 37/42 @s=128, 36/46 @s=512,
# 64/75 @s=2048, 84/2642 @s=8192.  The default therefore sits at the
# kernel's minimum tile (128); the env/field override remains for chips
# where the crossover differs.  Role mirror: the reference's shape-based
# algorithm selection (``ConvolutionLayer.java:349`` CudnnAlgoMode) —
# "auto" selects the measured-faster algorithm by shape.
DEFAULT_FLASH_MIN_SEQ = int(os.environ.get("DL4J_TPU_FLASH_MIN_SEQ", 128))


def _run_attention(q, k, v, *, impl: str, causal: bool, mask, seq_axis: str,
                   interpret: bool = False,
                   flash_min_seq: Optional[int] = None):
    """Dispatch [b,h,t,d] q/k/v to the selected attention implementation.

    ``impl='auto'`` picks by the measured crossover: reference SDPA for
    sequences shorter than ``flash_min_seq`` (default
    ``DEFAULT_FLASH_MIN_SEQ``, env ``DL4J_TPU_FLASH_MIN_SEQ``), flash at or
    above it.  Masked inputs always take the reference path (the kernel has
    no key-padding support)."""
    from ...ops.attention import sdpa_reference
    if impl not in _ATTN_IMPLS:
        raise ValueError(f"unknown attn_impl '{impl}'; expected one of "
                         f"{_ATTN_IMPLS}")
    if impl in ("ring", "ulysses"):
        from ...parallel.sequence import ring_self_attention, ulysses_attention
        if mask is not None:
            raise ValueError("sequence-parallel attention does not take "
                             "key-padding masks (pad to shard boundary)")
        fn = ring_self_attention if impl == "ring" else ulysses_attention
        return fn(q, k, v, axis_name=seq_axis, causal=causal)
    if impl == "flash":
        if mask is not None:
            raise ValueError("attn_impl='flash' does not take key-padding "
                             "masks; use 'reference'/'auto' or pre-mask inputs")
        from ...ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, interpret=interpret)
    if impl == "auto" and mask is None:
        threshold = (DEFAULT_FLASH_MIN_SEQ if flash_min_seq is None
                     else flash_min_seq)
        if q.shape[2] >= threshold:
            from ...ops.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=causal,
                                   interpret=interpret)
    return sdpa_reference(q, k, v, mask=mask, causal=causal)


def _kv_quantize(x):
    """Per-(row, head) absmax int8 quantization of a ``[..., d]`` K/V
    write: returns (q int8, scale f32 ``[...]``) with q*scale ≈ x."""
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = (jnp.maximum(amax, 1e-8) / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


@register_serde
@dataclass
class MultiHeadAttention(BaseLayerConf):
    """Multi-head self-attention over RNN-typed input [b, t, n_in].

    Projections pack all heads into single [n_in, h*d] matmuls (MXU-shaped);
    softmax statistics run in at least float32 even under bfloat16 params.

    HAS_CARRY: the carry is a KV cache ({k, v, pos}, capacity
    ``max_cache_len``) enabling incremental decoding through
    ``rnn_time_step`` — the attention-era face of the reference's stateful
    RNN inference.  Past ``max_cache_len`` the slice update saturates
    (oldest semantics undefined); size the cache for the longest sequence.
    """
    INPUT_KIND = "rnn"
    HAS_CARRY = True
    _BIAS_PARAMS = ("bq", "bk", "bv", "bo")

    n_in: int = 0
    n_out: int = 0              # model/embed dim of the output projection
    n_heads: int = 4
    head_dim: int = 0           # default n_out // n_heads
    causal: bool = False
    attn_impl: str = "auto"     # reference|flash|ring|ulysses|auto
    # 'auto' crossover override: flash at seq >= this (None = the measured
    # DEFAULT_FLASH_MIN_SEQ / env DL4J_TPU_FLASH_MIN_SEQ)
    flash_min_seq: Optional[int] = None
    seq_axis: str = "seq"
    has_bias: bool = True
    attn_dropout: Optional[float] = None   # retain prob on attention output
    max_cache_len: int = 512    # KV-cache capacity for incremental decode

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind != "rnn":
                raise ValueError(f"layer '{self.name}': MultiHeadAttention "
                                 f"expects RNN input, got {itype}")
            self.n_in = itype.size
        if self.n_out == 0:
            self.n_out = self.n_in

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.timesteps)

    def _dims(self):
        d = self.head_dim or max(1, self.n_out // self.n_heads)
        return self.n_heads, d

    def init(self, key, itype):
        h, d = self._dims()
        ks = jax.random.split(key, 4)
        params = {
            "Wq": self.make_weight(ks[0], (self.n_in, h * d)),
            "Wk": self.make_weight(ks[1], (self.n_in, h * d)),
            "Wv": self.make_weight(ks[2], (self.n_in, h * d)),
            "Wo": self.make_weight(ks[3], (h * d, self.n_out)),
        }
        if self.has_bias:
            params.update(bq=self.make_bias((h * d,)),
                          bk=self.make_bias((h * d,)),
                          bv=self.make_bias((h * d,)),
                          bo=self.make_bias((self.n_out,)))
        return {"params": params, "state": {}}

    def _heads(self, x, p, w, b):
        h, d = self._dims()
        y = x @ p[w]
        if self.has_bias:
            y = y + p[b]
        btime = y.shape[:-1]
        return y.reshape(*btime, h, d).transpose(0, 2, 1, 3)   # [b,h,t,d]

    def attend(self, p, x, *, train=False, key=None, mask=None):
        """QKV projection → attention → output projection on [b,t,f] input."""
        q = self._heads(x, p, "Wq", "bq")
        k = self._heads(x, p, "Wk", "bk")
        v = self._heads(x, p, "Wv", "bv")
        o = _run_attention(q, k, v, impl=self.attn_impl, causal=self.causal,
                           mask=mask, seq_axis=self.seq_axis,
                           flash_min_seq=self.flash_min_seq)
        b_, h, t, d = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b_, t, h * d)
        y = o @ p["Wo"]
        if self.has_bias:
            y = y + p["bo"]
        return self._maybe_attn_dropout(y, train, key)

    def _maybe_attn_dropout(self, y, train, key):
        if train and self.attn_dropout and key is not None:
            keep = self.attn_dropout
            mask_d = jax.random.bernoulli(jax.random.fold_in(key, 7), keep,
                                          y.shape)
            y = jnp.where(mask_d, y / keep, 0.0)
        return y

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        y = self.attend(p, x, train=train, key=key, mask=mask)
        return self.act_fn(y), variables.get("state", {})

    # ---- KV-cache incremental decoding -----------------------------------
    def init_carry(self, batch: int, dtype=jnp.float32,
                   max_len: Optional[int] = None):
        """Zero carry.  ``max_len`` overrides the cache capacity (the
        generation subsystem sizes prefill carries to the prompt bucket
        and slot caches to the engine's ``max_seq``); ``attend_cached``
        derives the capacity from the carry itself, so carries of any
        length ride the same code."""
        h, d = self._dims()
        L = self.max_cache_len if max_len is None else int(max_len)
        return {"k": jnp.zeros((batch, h, L, d), dtype),
                "v": jnp.zeros((batch, h, L, d), dtype),
                "m": jnp.zeros((batch, L), jnp.float32),   # cache validity
                "pos": jnp.zeros((), jnp.int32)}

    def attend_cached(self, p, x, carry, *, mask=None):
        """Project the t new steps, extend the cache, attend q against the
        full prefix (``sdpa_reference`` with q_offset — one SDPA
        implementation).  Honors self.causal and key-padding masks; masked
        positions are recorded invalid in the cache.  Returns
        (y [b,t,n_out], new_carry).

        ``carry["pos"]`` is a scalar (every row at the same stream
        position — tBPTT chunks, ``rnn_time_step``) or a ``[b]`` vector
        (per-row positions — the generation engine's slot-batched decode,
        where every slot sits at its own sequence offset).  The vector
        form supports single-token steps only (t == 1): causality then
        reduces to the written-prefix mask, so one fixed-shape decode
        program serves every slot mix.

        A carry holding ``kp`` (a paged block pool) dispatches to
        :meth:`_attend_paged` instead — same contract, K/V gathered
        through a block table."""
        from ...ops.attention import sdpa_reference
        if isinstance(carry, dict) and "kp" in carry:
            return self._attend_paged(p, x, carry, mask=mask)
        q = self._heads(x, p, "Wq", "bq")                 # [b,h,t,d]
        k_new = self._heads(x, p, "Wk", "bk")
        v_new = self._heads(x, p, "Wv", "bv")
        pos = carry["pos"]
        L = carry["k"].shape[2]        # capacity from the carry, not conf
        t = q.shape[2]
        b_ = x.shape[0]
        chunk_valid = (jnp.ones((b_, t), jnp.float32) if mask is None
                       else mask.astype(jnp.float32))
        if getattr(pos, "ndim", 0) == 1:
            if t != 1:
                raise ValueError(
                    "per-row vector pos carries support single-token decode "
                    f"only (t=1), got a {t}-step chunk")
            z = jnp.zeros((), pos.dtype)
            k = jax.vmap(lambda c, n, p_: jax.lax.dynamic_update_slice(
                c, n, (z, p_, z)))(carry["k"],
                                   k_new.astype(carry["k"].dtype), pos)
            v = jax.vmap(lambda c, n, p_: jax.lax.dynamic_update_slice(
                c, n, (z, p_, z)))(carry["v"],
                                   v_new.astype(carry["v"].dtype), pos)
            m = jax.vmap(lambda mm, cv, p_: jax.lax.dynamic_update_slice(
                mm, cv, (p_,)))(carry["m"], chunk_valid, pos)
            written = (jnp.arange(L)[None, :]
                       < (pos + t)[:, None]).astype(jnp.float32)   # [b, L]
            key_mask = m * written
            # t == 1: the single query sits at the newest position, so the
            # written-prefix mask IS the causal mask — no q_offset needed
            o = sdpa_reference(q, k.astype(q.dtype), v.astype(q.dtype),
                               mask=key_mask, causal=False)
        else:
            z = jnp.zeros((), pos.dtype)   # index dtypes must match (x64)
            k = jax.lax.dynamic_update_slice(
                carry["k"], k_new.astype(carry["k"].dtype), (z, z, pos, z))
            v = jax.lax.dynamic_update_slice(
                carry["v"], v_new.astype(carry["v"].dtype), (z, z, pos, z))
            m = jax.lax.dynamic_update_slice(carry["m"], chunk_valid,
                                             (z, pos))
            written = (jnp.arange(L) < pos + t).astype(jnp.float32)   # [L]
            key_mask = m * written[None, :]                            # [b, L]
            o = sdpa_reference(q, k.astype(q.dtype), v.astype(q.dtype),
                               mask=key_mask, causal=self.causal,
                               q_offset=pos)
        o = o.transpose(0, 2, 1, 3).reshape(b_, t, -1)
        y = o @ p["Wo"]
        if self.has_bias:
            y = y + p["bo"]
        if mask is not None:   # zero outputs at padded query steps
            y = y * mask.astype(y.dtype)[:, :, None]
        return y, {"k": k, "v": v, "m": m, "pos": pos + t}

    @staticmethod
    def _gather_pool(pool, scales, table, dtype):
        """Materialize ``[S, h, V, d]`` keys/values by gathering pool
        blocks through an ``[S, NB]`` block table (V = NB * block_size;
        virtual position == token position).  int8 pools dequantize
        against their ``[n_blocks, h, block]`` scales here — quantized
        storage, full-precision math."""
        g = pool[table]                            # [S, NB, h, blk, d]
        if scales is not None:
            g = g.astype(jnp.float32) * scales[table][..., None]
        s_, nb, h, blk, d = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(s_, h, nb * blk,
                                                  d).astype(dtype)

    def _attend_paged(self, p, x, carry, *, mask=None):
        """Gather-through-table attention over the paged KV block pool
        (``generation/cache.PagedKV``).  Carry schema: ``kp``/``vp``
        ``[n_blocks, h, block, d]`` pools (int8 pools add ``ksc``/``vsc``
        ``[n_blocks, h, block]`` scales) plus the block ``table`` and
        ``pos`` — ``[S, NB]`` table with vector ``[S]`` positions for the
        fixed-shape decode step, ``[NB]`` row with a scalar suffix start
        for shared-prefix prefill.  Tables and positions are DATA, never
        shapes, so every slot/block mix rides one compiled program.

        Writes land at ``table[pos // block], pos % block``; padded and
        inactive lanes redirect to physical block 0 (the trash block —
        reserved, never allocated, mask-dead).  Reads gather the full
        virtual axis ``V = NB * block`` with virtual position == token
        position, so the written-prefix mask is exactly the dense ring's
        mask and the softmax sees the same finite entries in the same
        order — masked tail entries contribute exact zeros, which is
        what makes paged-vs-dense token streams bit-identical on
        sequential-reduction backends."""
        from ...ops.attention import sdpa_reference
        q = self._heads(x, p, "Wq", "bq")                 # [b,h,t,d]
        k_new = self._heads(x, p, "Wk", "bk")
        v_new = self._heads(x, p, "Wv", "bv")
        kp, vp = carry["kp"], carry["vp"]
        table, pos = carry["table"], carry["pos"]
        quant = kp.dtype == jnp.int8
        blk = kp.shape[2]
        t = q.shape[2]
        b_ = x.shape[0]
        chunk_valid = (jnp.ones((b_, t), jnp.float32) if mask is None
                       else mask.astype(jnp.float32))
        new_carry = dict(carry)
        if getattr(pos, "ndim", 0) == 1:
            # decode: one token per slot, per-slot positions, [S, NB]
            if t != 1:
                raise ValueError(
                    "per-slot vector pos supports single-token decode "
                    f"only (t=1), got a {t}-step chunk")
            nb = table.shape[1]
            bidx = jnp.clip(pos // blk, 0, nb - 1)
            phys = jnp.take_along_axis(table, bidx[:, None], axis=1)[:, 0]
            off = pos % blk
            kw = k_new[:, :, 0, :]                        # [S, h, d]
            vw = v_new[:, :, 0, :]
            tab2 = table
            written = (jnp.arange(nb * blk)[None, :]
                       < (pos + t)[:, None]).astype(jnp.float32)
            causal, q_offset = False, 0
        else:
            # shared-prefix prefill: batch 1, t suffix steps from `pos`
            nb = table.shape[0]
            p_j = pos + jnp.arange(t, dtype=jnp.int32)
            bidx = jnp.clip(p_j // blk, 0, nb - 1)
            phys = jnp.where(chunk_valid[0] > 0, table[bidx], 0)
            off = p_j % blk
            kw = k_new[0].transpose(1, 0, 2)              # [t, h, d]
            vw = v_new[0].transpose(1, 0, 2)
            tab2 = table[None, :]
            v_ax = nb * blk
            prefix = (jnp.arange(v_ax, dtype=jnp.int32)
                      < pos).astype(jnp.float32)
            chunk_m = jax.lax.dynamic_update_slice(
                jnp.zeros((v_ax,), jnp.float32), chunk_valid[0], (pos,))
            written = jnp.clip(prefix + chunk_m, 0.0, 1.0)[None, :]
            causal, q_offset = self.causal, pos
        if quant:
            kq, ks = _kv_quantize(kw)
            vq, vs = _kv_quantize(vw)
            kp = kp.at[phys, :, off, :].set(kq)
            vp = vp.at[phys, :, off, :].set(vq)
            new_carry["ksc"] = carry["ksc"].at[phys, :, off].set(ks)
            new_carry["vsc"] = carry["vsc"].at[phys, :, off].set(vs)
        else:
            kp = kp.at[phys, :, off, :].set(kw.astype(kp.dtype))
            vp = vp.at[phys, :, off, :].set(vw.astype(vp.dtype))
        k = self._gather_pool(kp, new_carry.get("ksc"), tab2, q.dtype)
        v = self._gather_pool(vp, new_carry.get("vsc"), tab2, q.dtype)
        o = sdpa_reference(q, k, v, mask=written, causal=causal,
                           q_offset=q_offset)
        new_carry.update(kp=kp, vp=vp, pos=pos + t)
        o = o.transpose(0, 2, 1, 3).reshape(b_, t, -1)
        y = o @ p["Wo"]
        if self.has_bias:
            y = y + p["bo"]
        if mask is not None:   # zero outputs at padded query steps
            y = y * mask.astype(y.dtype)[:, :, None]
        return y, new_carry

    def apply_with_carry(self, variables, x, carry, *, train=False,
                         key=None, mask=None):
        if carry is None:
            carry = self.init_carry(x.shape[0], x.dtype)
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        y, new_carry = self.attend_cached(p, x, carry, mask=mask)
        y = self._maybe_attn_dropout(y, train, key)
        return self.act_fn(y), new_carry


@register_serde
@dataclass
class TransformerBlock(BaseLayerConf):
    """Pre-norm transformer block: LN→MHA→residual, LN→MLP(GELU)→residual.

    The attention half delegates to ``MultiHeadAttention`` (params carried
    under a ``mha_`` prefix) so the two layers share one projection/head
    implementation; ffn_mult sizes the hidden MLP.
    """
    INPUT_KIND = "rnn"
    HAS_CARRY = True
    _BIAS_PARAMS = ("mha_bq", "mha_bk", "mha_bv", "mha_bo", "b1", "b2",
                    "ln1_g", "ln1_b", "ln2_g", "ln2_b")

    n_in: int = 0
    n_heads: int = 4
    ffn_mult: int = 4
    causal: bool = True
    attn_impl: str = "auto"
    flash_min_seq: Optional[int] = None   # 'auto' crossover override
    seq_axis: str = "seq"
    eps: float = 1e-5
    max_cache_len: int = 512
    # Switch-transformer style sparse FFN: >0 replaces the dense MLP with
    # a top-1 routed expert stack (aux loss threads through state)
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @property
    def AUX_LOSS(self):
        return self.moe_experts > 0

    def set_n_in(self, itype: InputType, override: bool = False) -> None:
        if self.n_in == 0 or override:
            if itype.kind != "rnn":
                raise ValueError(f"layer '{self.name}': TransformerBlock "
                                 f"expects RNN input, got {itype}")
            self.n_in = itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_in, itype.timesteps)

    def _mha(self) -> MultiHeadAttention:
        m = MultiHeadAttention(
            n_in=self.n_in, n_out=self.n_in, n_heads=self.n_heads,
            causal=self.causal, attn_impl=self.attn_impl,
            flash_min_seq=self.flash_min_seq,
            seq_axis=self.seq_axis, activation="identity",
            weight_init=self.weight_init, weight_dist=self.weight_dist,
            bias_init=self.bias_init, dtype=self.dtype,
            max_cache_len=self.max_cache_len)
        return m

    def init(self, key, itype):
        e = self.n_in
        f = self.ffn_mult * e
        k_mha, k1, k2, kr = jax.random.split(key, 4)
        mha_vars = self._mha().init(k_mha, itype)
        params = {f"mha_{k}": v for k, v in mha_vars["params"].items()}
        if self.moe_experts > 0:
            E = self.moe_experts
            params.update({
                "router": self.make_weight(kr, (e, E)),
                "w1": self.make_weight(k1, (E, e, f)),
                "b1": self.make_bias((E, 1, f)),
                "w2": self.make_weight(k2, (E, f, e)),
                "b2": self.make_bias((E, 1, e)),
            })
        else:
            params.update({
                "W1": self.make_weight(k1, (e, f)),
                "b1": self.make_bias((f,)),
                "W2": self.make_weight(k2, (f, e)),
                "b2": self.make_bias((e,)),
            })
        params.update({
            "ln1_g": jnp.ones((e,), self._dtype()),
            "ln1_b": jnp.zeros((e,), self._dtype()),
            "ln2_g": jnp.ones((e,), self._dtype()),
            "ln2_b": jnp.zeros((e,), self._dtype()),
        })
        state = {}
        if self.moe_experts > 0:
            state["aux_loss"] = jnp.zeros((), self._dtype())
        return {"params": params, "state": state}

    def _ffn(self, p, xn):
        """Dense or routed MLP; returns (out, state_update)."""
        if self.moe_experts == 0:
            return (jax.nn.gelu(xn @ p["W1"] + p["b1"]) @ p["W2"]
                    + p["b2"], {})
        from ...parallel.expert import moe_ffn
        b, t, e = xn.shape
        x2d = xn.reshape(b * t, e)
        capacity = max(int(self.moe_capacity_factor * b * t
                           / self.moe_experts), 1)
        moe_p = {"router": p["router"], "w1": p["w1"], "b1": p["b1"],
                 "w2": p["w2"], "b2": p["b2"]}
        y, aux = moe_ffn(moe_p, x2d, capacity, act=jax.nn.gelu)
        return y.reshape(b, t, e), {
            "aux_loss": (self.aux_loss_weight * aux).astype(
                jnp.result_type(xn))}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        mha_p = {k[4:]: v for k, v in p.items() if k.startswith("mha_")}

        xn = _layer_norm(x, p["ln1_g"], p["ln1_b"], self.eps)
        x = x + self._mha().attend(mha_p, xn, train=train, key=key, mask=mask)

        xn = _layer_norm(x, p["ln2_g"], p["ln2_b"], self.eps)
        ff, st = self._ffn(p, xn)
        return x + ff, st if st else variables.get("state", {})

    # ---- KV-cache incremental decoding -----------------------------------
    def init_carry(self, batch: int, dtype=jnp.float32,
                   max_len: Optional[int] = None):
        return self._mha().init_carry(batch, dtype, max_len=max_len)

    def apply_with_carry(self, variables, x, carry, *, train=False,
                         key=None, mask=None):
        if carry is None:
            carry = self.init_carry(x.shape[0], x.dtype)
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        mha_p = {k[4:]: v for k, v in p.items() if k.startswith("mha_")}
        xn = _layer_norm(x, p["ln1_g"], p["ln1_b"], self.eps)
        attn, new_carry = self._mha().attend_cached(mha_p, xn, carry,
                                                    mask=mask)
        x = x + attn
        xn = _layer_norm(x, p["ln2_g"], p["ln2_b"], self.eps)
        ff, st = self._ffn(p, xn)
        if st:
            # thread the MoE aux loss out through the caller's mutable
            # variables dict (the MLN carry path reads state after the call)
            variables["state"] = st
        return x + ff, new_carry


@register_serde
@dataclass
class PositionalEncodingLayer(LayerConf):
    """Sinusoidal positional encoding added to RNN-typed input (no params).
    Carry = stream position, so incremental decode keeps absolute
    positions."""
    HAS_CARRY = True

    def output_type(self, itype: InputType) -> InputType:
        return itype

    @staticmethod
    def _pe(t, e, offset, dtype):
        """Sinusoidal table for ``t`` steps starting at ``offset`` —
        a scalar (one shared stream position: [t, e]) or a ``[b]`` vector
        (per-row positions, the slot-batched decode step: [b, t, e])."""
        offset = jnp.asarray(offset, jnp.float32)
        pos = offset[..., None] + jnp.arange(t, dtype=jnp.float32)
        i = jnp.arange(e, dtype=jnp.float32)
        angle = pos[..., None] / jnp.power(10000.0, (2 * (i // 2)) / e)
        return jnp.where(i % 2 == 0, jnp.sin(angle),
                         jnp.cos(angle)).astype(dtype)

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        b, t, e = x.shape
        return x + self._pe(t, e, 0.0, x.dtype), variables.get("state", {})

    def init_carry(self, batch: int, dtype=jnp.float32,
                   max_len: Optional[int] = None):
        return {"pos": jnp.zeros((), jnp.int32)}

    def apply_with_carry(self, variables, x, carry, *, train=False,
                         key=None, mask=None):
        if carry is None:
            carry = self.init_carry(x.shape[0], x.dtype)
        b, t, e = x.shape
        y = x + self._pe(t, e, carry["pos"].astype(jnp.float32), x.dtype)
        return y, {"pos": carry["pos"] + t}
