"""Unsupervised / pretrainable layers: AutoEncoder, RBM, VariationalAutoencoder.

Reference:
  - ``nn/layers/feedforward/autoencoder/AutoEncoder.java`` (denoising AE,
    corruption via dropout-style masking)
  - ``nn/layers/feedforward/rbm/RBM.java`` (CD-k contrastive divergence)
  - ``nn/layers/variational/VariationalAutoencoder.java:51`` (multi-layer
    encoder/decoder, pluggable reconstruction distribution)

Layers declare ``PRETRAINABLE = True`` and provide
``pretrain_loss(variables, x, *, key, train) -> scalar``; the networks'
``pretrain()`` drives per-layer greedy training (reference
``MultiLayerNetwork.pretrain`` :1173).  TPU notes: the RBM's CD-k gradient is
expressed as the free-energy-difference surrogate so ``jax.grad`` reproduces
the CD update without hand-written positive/negative phase code; sampling
noise comes from explicit PRNG keys (trace-safe).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from .. import activations as _act
from .. import losses as _losses
from ..conf.input_type import InputType
from ..conf.variational import (BernoulliReconstructionDistribution,
                                ReconstructionDistribution)
from .base import BaseLayerConf, split_key

Array = jax.Array


@register_serde
@dataclass
class AutoEncoder(BaseLayerConf):
    """Denoising autoencoder: encode = act(xW+b); decode through W^T.
    ``corruption_level`` masks that fraction of inputs during pretraining;
    ``sparsity`` adds a KL sparsity penalty on mean hidden activation."""
    n_in: int = 0
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    sparsity_target: float = 0.05
    visible_loss: str = "mse"      # "mse" | "xent"

    PRETRAINABLE = True
    INPUT_KIND = "ff"              # auto-insert CNN→FF preprocessor

    def set_n_in(self, itype, override=False):
        if self.n_in == 0 or override:
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(f"AutoEncoder '{self.name}': set n_in/n_out")
        params = {"W": self.make_weight(key, (self.n_in, self.n_out)),
                  "b": self.make_bias((self.n_out,)),
                  "vb": self.make_bias((self.n_in,))}
        return {"params": params, "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        return self.act_fn(x @ p["W"] + p["b"]), variables.get("state", {})

    def pretrain_loss(self, variables, x, *, key=None, train=True):
        p = variables["params"]
        xin = x
        if train and self.corruption_level > 0 and key is not None:
            keep = jax.random.bernoulli(
                key, 1.0 - self.corruption_level, x.shape)
            xin = x * keep
        h = self.act_fn(xin @ p["W"] + p["b"])
        z = h @ p["W"].T + p["vb"]
        loss = _losses.get(self.visible_loss)(
            x, z, "sigmoid" if self.visible_loss == "xent" else "identity",
            None)
        if self.sparsity > 0:
            rho, rho_hat = self.sparsity_target, jnp.clip(
                jnp.mean(h, axis=0), 1e-6, 1 - 1e-6)
            kl = rho * jnp.log(rho / rho_hat) + \
                (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat))
            loss = loss + self.sparsity * jnp.sum(kl)
        return loss


@register_serde
@dataclass
class RBM(BaseLayerConf):
    """Restricted Boltzmann machine, CD-k pretraining.

    Gradient trick: loss = mean(F(v_data) - F(v_model)) with the Gibbs chain
    sample ``v_model`` under stop_gradient — jax.grad of this is exactly the
    CD-k update the reference computes by hand (positive phase - negative
    phase), F(v) = -v·vb - Σ softplus(vW + hb)."""
    n_in: int = 0
    n_out: int = 0
    k: int = 1
    hidden_unit: str = "binary"    # "binary" | "rectified"
    visible_unit: str = "binary"   # "binary" | "gaussian"

    PRETRAINABLE = True
    INPUT_KIND = "ff"

    def set_n_in(self, itype, override=False):
        if self.n_in == 0 or override:
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(f"RBM '{self.name}': set n_in/n_out")
        params = {"W": self.make_weight(key, (self.n_in, self.n_out)),
                  "b": self.make_bias((self.n_out,)),
                  "vb": self.make_bias((self.n_in,))}
        return {"params": params, "state": {}}

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        pre = x @ p["W"] + p["b"]
        h = jax.nn.relu(pre) if self.hidden_unit == "rectified" \
            else jax.nn.sigmoid(pre)
        return h, variables.get("state", {})

    def _free_energy(self, p, v):
        vis = v @ p["vb"]
        if self.visible_unit == "gaussian":
            vis = vis - 0.5 * jnp.sum(v * v, axis=-1)
        hid = jnp.sum(jax.nn.softplus(v @ p["W"] + p["b"]), axis=-1)
        return -vis - hid

    def _gibbs_step(self, p, v, key):
        kh, kv = jax.random.split(key)
        ph = jax.nn.sigmoid(v @ p["W"] + p["b"])
        h = jax.random.bernoulli(kh, ph).astype(v.dtype)
        pre_v = h @ p["W"].T + p["vb"]
        if self.visible_unit == "gaussian":
            v2 = pre_v + jax.random.normal(kv, pre_v.shape, pre_v.dtype)
        else:
            pv = jax.nn.sigmoid(pre_v)
            v2 = jax.random.bernoulli(kv, pv).astype(v.dtype)
        return v2

    def pretrain_loss(self, variables, x, *, key=None, train=True):
        if self.hidden_unit != "binary":
            raise ValueError(
                "RBM CD-k pretraining implements binary hidden units only; "
                "the free-energy objective below would not match "
                f"hidden_unit='{self.hidden_unit}' (rectified units are "
                "supported for forward feature extraction)")
        p = variables["params"]
        if key is None:
            key = jax.random.PRNGKey(0)
        v = x
        for i in range(max(1, self.k)):
            v = self._gibbs_step(p, v, jax.random.fold_in(key, i))
        v_model = jax.lax.stop_gradient(v)
        return jnp.mean(self._free_energy(p, x) -
                        self._free_energy(p, v_model))


@register_serde
@dataclass
class VariationalAutoencoder(BaseLayerConf):
    """VAE layer: multi-layer encoder → (mean, logvar) → z → multi-layer
    decoder → reconstruction distribution.  Supervised forward = mean of
    q(z|x) (reference ``VariationalAutoencoder.activate``); pretraining
    maximizes the ELBO with the reparameterization trick."""
    n_in: int = 0
    n_out: int = 0                               # latent size (nOut == nLatent)
    encoder_layer_sizes: List[int] = field(default_factory=lambda: [100])
    decoder_layer_sizes: List[int] = field(default_factory=lambda: [100])
    pzx_activation: str = "identity"
    reconstruction_distribution: Any = field(
        default_factory=BernoulliReconstructionDistribution)
    num_samples: int = 1

    PRETRAINABLE = True
    INPUT_KIND = "ff"

    def set_n_in(self, itype, override=False):
        if self.n_in == 0 or override:
            self.n_in = itype.flat_size() if itype.kind == "cnnflat" else itype.size

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        if self.n_in <= 0 or self.n_out <= 0:
            raise ValueError(f"VAE '{self.name}': set n_in/n_out")
        params = {}
        keys = split_key(key, len(self.encoder_layer_sizes) +
                         len(self.decoder_layer_sizes) + 4)
        ki = 0
        last = self.n_in
        for i, size in enumerate(self.encoder_layer_sizes):
            params[f"e{i}_W"] = self.make_weight(keys[ki], (last, size))
            params[f"e{i}_b"] = self.make_bias((size,))
            ki += 1
            last = size
        params["mean_W"] = self.make_weight(keys[ki], (last, self.n_out)); ki += 1
        params["mean_b"] = self.make_bias((self.n_out,))
        params["logvar_W"] = self.make_weight(keys[ki], (last, self.n_out)); ki += 1
        params["logvar_b"] = self.make_bias((self.n_out,))
        last = self.n_out
        for i, size in enumerate(self.decoder_layer_sizes):
            params[f"d{i}_W"] = self.make_weight(keys[ki], (last, size))
            params[f"d{i}_b"] = self.make_bias((size,))
            ki += 1
            last = size
        pdist = self.reconstruction_distribution.dist_params_size(self.n_in)
        params["out_W"] = self.make_weight(keys[ki], (last, pdist))
        params["out_b"] = self.make_bias((pdist,))
        return {"params": params, "state": {}}

    # ---- internals ----
    def _encode(self, p, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self.act_fn(h @ p[f"e{i}_W"] + p[f"e{i}_b"])
        mean = h @ p["mean_W"] + p["mean_b"]
        log_var = h @ p["logvar_W"] + p["logvar_b"]
        return mean, log_var

    def _decode(self, p, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self.act_fn(h @ p[f"d{i}_W"] + p[f"d{i}_b"])
        return h @ p["out_W"] + p["out_b"]

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        p = self.maybe_noise_weights(key, variables["params"], train)
        x = self.maybe_dropout_input(key, x, train)
        mean, _ = self._encode(p, x)
        return _act.get(self.pzx_activation)(mean), variables.get("state", {})

    def pretrain_loss(self, variables, x, *, key=None, train=True):
        p = variables["params"]
        mean, log_var = self._encode(p, x)
        log_var = jnp.clip(log_var, -20.0, 20.0)
        kl = 0.5 * jnp.sum(jnp.exp(log_var) + mean ** 2 - 1.0 - log_var,
                           axis=-1)
        # accumulate in the activation dtype (dtype-defaulted zeros(())
        # is f64 under x64 — graftaudit AX001)
        recon = jnp.zeros((), dtype=mean.dtype)
        n = max(1, self.num_samples)
        for s in range(n):
            if key is not None and train:
                eps = jax.random.normal(jax.random.fold_in(key, s),
                                        mean.shape, mean.dtype)
            else:
                eps = jnp.zeros_like(mean)
            z = mean + jnp.exp(0.5 * log_var) * eps
            preout = self._decode(p, z)
            recon = recon + self.reconstruction_distribution.neg_log_prob(
                x, preout, average=True)
        return recon / n + jnp.mean(kl)

    # ---- generation (reference generateAtMeanGivenZ / reconstruction api) --
    def generate_at_mean_given_z(self, variables, z):
        return self.reconstruction_distribution.mean(
            self._decode(variables["params"], z))

    def generate_random_given_z(self, variables, z, key):
        return self.reconstruction_distribution.sample(
            key, self._decode(variables["params"], z))

    def reconstruction_probability(self, variables, x, key, num_samples=5):
        """Monte-carlo estimate of log p(x) (reference
        ``reconstructionLogProbability``), per example."""
        p = variables["params"]
        mean, log_var = self._encode(p, x)
        log_var = jnp.clip(log_var, -20.0, 20.0)
        std = jnp.exp(0.5 * log_var)
        lls = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(key, s),
                                    mean.shape, mean.dtype)
            z = mean + std * eps
            preout = self._decode(p, z)
            # importance-weighted single-sample log p(x|z) + log p(z) - log q(z|x)
            log_pxz = -self._per_example_nlp(x, preout)
            log_pz = -0.5 * jnp.sum(z ** 2 + jnp.log(2 * jnp.pi), axis=-1)
            log_qzx = -0.5 * jnp.sum(
                ((z - mean) / std) ** 2 + 2 * jnp.log(std) +
                jnp.log(2 * jnp.pi), axis=-1)
            lls.append(log_pxz + log_pz - log_qzx)
        stacked = jnp.stack(lls)
        return jax.nn.logsumexp(stacked, axis=0) - jnp.log(float(num_samples))

    def _per_example_nlp(self, x, preout):
        # neg_log_prob averaged → recover per-example via vmap over rows
        return jax.vmap(
            lambda xi, pi: self.reconstruction_distribution.neg_log_prob(
                xi[None], pi[None], average=False))(x, preout)
