"""Layer configs/implementations (reference ``nn/conf/layers`` + ``nn/layers``)."""
from .base import BaseLayerConf, LayerConf
from .convolution import (Convolution1DLayer, ConvolutionLayer,
                          Subsampling1DLayer, SubsamplingLayer, Upsampling1D,
                          Upsampling2D, ZeroPaddingLayer)
from .feedforward import (ActivationLayer, DenseLayer, DropoutLayer,
                          EmbeddingLayer, LossLayer, OutputLayer)
from .normalization import BatchNormalization, LocalResponseNormalization
from .pooling import GlobalPoolingLayer

__all__ = [
    "ActivationLayer", "BaseLayerConf", "BatchNormalization",
    "Convolution1DLayer", "ConvolutionLayer", "DenseLayer", "DropoutLayer",
    "EmbeddingLayer", "GlobalPoolingLayer", "LayerConf",
    "LocalResponseNormalization", "LossLayer", "OutputLayer",
    "Subsampling1DLayer", "SubsamplingLayer", "Upsampling1D", "Upsampling2D",
    "ZeroPaddingLayer",
]
