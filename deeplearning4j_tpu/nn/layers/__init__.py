"""Layer configs/implementations (reference ``nn/conf/layers`` + ``nn/layers``)."""
from .base import BaseLayerConf, LayerConf
from .convolution import (Convolution1DLayer, ConvolutionLayer,
                          Subsampling1DLayer, SubsamplingLayer, Upsampling1D,
                          Upsampling2D, ZeroPaddingLayer)
from .feedforward import (ActivationLayer, DenseLayer, DropoutLayer,
                          EmbeddingLayer, LossLayer, OutputLayer)
from .normalization import BatchNormalization, LocalResponseNormalization
from .pooling import GlobalPoolingLayer
from .recurrent import (Bidirectional, GravesBidirectionalLSTM, GravesLSTM,
                        LastTimeStep, LSTM, RnnOutputLayer, SimpleRnn)

__all__ = [
    "ActivationLayer", "BaseLayerConf", "BatchNormalization", "Bidirectional",
    "Convolution1DLayer", "ConvolutionLayer", "DenseLayer", "DropoutLayer",
    "EmbeddingLayer", "GlobalPoolingLayer", "GravesBidirectionalLSTM",
    "GravesLSTM", "LastTimeStep", "LayerConf", "LocalResponseNormalization",
    "LossLayer", "LSTM", "OutputLayer", "RnnOutputLayer", "SimpleRnn",
    "Subsampling1DLayer", "SubsamplingLayer", "Upsampling1D", "Upsampling2D",
    "ZeroPaddingLayer",
]
