"""Layer configs/implementations (reference ``nn/conf/layers`` + ``nn/layers``)."""
from .attention import (LayerNormLayer, MultiHeadAttention,
                        PositionalEncodingLayer, TransformerBlock)
from .base import BaseLayerConf, LayerConf
from .convolution import (Convolution1DLayer, ConvolutionLayer,
                          Subsampling1DLayer, SubsamplingLayer, Upsampling1D,
                          Upsampling2D, ZeroPaddingLayer)
from .feedforward import (ActivationLayer, CenterLossOutputLayer, DenseLayer,
                          DropoutLayer, EmbeddingLayer,
                          EmbeddingSequenceLayer, LossLayer, OutputLayer)
from .misc import FrozenLayer
from .moe import MixtureOfExpertsLayer
from .normalization import BatchNormalization, LocalResponseNormalization
from .objdetect import Yolo2OutputLayer
from .pooling import GlobalPoolingLayer
from .pretrain import AutoEncoder, RBM, VariationalAutoencoder
from .recurrent import (Bidirectional, GravesBidirectionalLSTM, GravesLSTM,
                        LastTimeStep, LSTM, RnnOutputLayer, SimpleRnn)

__all__ = [
    "ActivationLayer", "AutoEncoder", "BaseLayerConf", "BatchNormalization",
    "Bidirectional", "CenterLossOutputLayer", "Convolution1DLayer",
    "ConvolutionLayer", "DenseLayer", "DropoutLayer", "EmbeddingLayer",
    "EmbeddingSequenceLayer",
    "FrozenLayer", "GlobalPoolingLayer", "GravesBidirectionalLSTM",
    "GravesLSTM", "LastTimeStep", "LayerConf", "LayerNormLayer",
    "LocalResponseNormalization", "LossLayer", "LSTM",
    "MixtureOfExpertsLayer", "MultiHeadAttention",
    "OutputLayer", "PositionalEncodingLayer", "RBM", "RnnOutputLayer",
    "SimpleRnn", "TransformerBlock",
    "Subsampling1DLayer", "SubsamplingLayer", "Upsampling1D", "Upsampling2D",
    "VariationalAutoencoder", "Yolo2OutputLayer", "ZeroPaddingLayer",
]
