"""Misc layer wrappers — FrozenLayer.

Reference ``nn/layers/FrozenLayer.java`` + ``nn/conf/layers/misc/FrozenLayer.java``:
a wrapper that runs the underlying layer's forward pass but never updates its
params.  Functional JAX version: ``stop_gradient`` on the wrapped params inside
``apply`` (gradients are structurally zero), and the updater machinery
additionally labels frozen groups with ``optax.set_to_zero`` so no updater
state is carried for them.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.serde import register_serde
from ..conf.input_type import InputType
from .base import LayerConf


@register_serde
@dataclass
class FrozenLayer(LayerConf):
    """Freeze the wrapped layer's params (training no-op, inference normal)."""
    underlying: Optional[LayerConf] = None

    FROZEN = True

    @property
    def INPUT_KIND(self):  # auto-preprocessor insertion sees the real kind
        return getattr(self.underlying, "INPUT_KIND", "any")

    @property
    def HAS_CARRY(self):
        return getattr(self.underlying, "HAS_CARRY", False)

    def init_carry(self, batch, dtype=jnp.float32, max_len=None):
        # forward the generation-side capacity override to carry layers
        # that take it (attention KV caches are sized by max_len, not
        # their conf default); plain RNN carries keep the 2-arg form
        if max_len is not None:
            return self.underlying.init_carry(batch, dtype,
                                              max_len=max_len)
        return self.underlying.init_carry(batch, dtype)

    def apply_with_carry(self, variables, x, carry, *, train=False, key=None,
                         mask=None):
        variables = self._frozen_vars(variables)
        return self.underlying.apply_with_carry(variables, x, carry,
                                                train=train, key=key, mask=mask)

    def _frozen_vars(self, variables):
        return {"params": jax.tree_util.tree_map(jax.lax.stop_gradient,
                                                 variables.get("params", {})),
                "state": variables.get("state", {})}

    def has_params(self):
        return self.underlying.has_params()

    def apply_global_defaults(self, defaults):
        if hasattr(self.underlying, "apply_global_defaults"):
            self.underlying.apply_global_defaults(defaults)

    def set_n_in(self, itype, override=False):
        self.underlying.set_n_in(itype, override)

    def output_type(self, itype: InputType) -> InputType:
        return self.underlying.output_type(itype)

    def init(self, key, itype):
        return self.underlying.init(key, itype)

    def regularization_score(self, params):
        # frozen params don't contribute to the loss (their l1/l2 is constant
        # w.r.t. training and would only shift the reported score); f32 so
        # x64 can't promote the loss through it (graftaudit AX001)
        return jnp.zeros((), jnp.float32)

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        # train=False for the wrapped layer: a frozen layer behaves in
        # inference mode (no dropout; BN uses global stats) — reference
        # FrozenLayer delegates with training disabled
        return self.underlying.apply(self._frozen_vars(variables), x,
                                     train=False, key=key, mask=mask)

    def compute_loss(self, variables, x, labels, *, train=False, key=None,
                     mask=None):
        return self.underlying.compute_loss(self._frozen_vars(variables), x,
                                            labels, train=False, key=key,
                                            mask=mask)

    def feed_forward_mask(self, mask, itype):
        return self.underlying.feed_forward_mask(mask, itype)


@register_serde
@dataclass
class ReshapeLayer(LayerConf):
    """Per-example reshape (role of Keras ``Reshape``; the reference maps it
    via ``KerasReshape`` preprocessors, ``deeplearning4j-modelimport``).
    ``target_shape``: per-example dims — rank 1 → ff, 2 → rnn [t, f]
    (time-major per-example, stored batch-major), 3 → cnn [h, w, c]."""
    INPUT_KIND = "any"

    target_shape: tuple = ()

    def output_type(self, itype: InputType) -> InputType:
        t = tuple(int(d) for d in self.target_shape)
        if len(t) == 1:
            return InputType.feed_forward(t[0])
        if len(t) == 2:
            return InputType.recurrent(t[1], t[0])
        if len(t) == 3:
            return InputType.convolutional(t[0], t[1], t[2])
        raise ValueError(f"ReshapeLayer: unsupported rank {len(t)}")

    def feed_forward_mask(self, mask, itype):
        # the reshape reinterprets (or removes) the time axis, so an
        # incoming per-timestep mask has no meaningful image — drop it
        return None

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return (x.reshape((x.shape[0],) + tuple(self.target_shape)),
                variables.get("state", {}))


@register_serde
@dataclass
class PermuteLayer(LayerConf):
    """Per-example axis permutation (Keras ``Permute``; 1-indexed dims over
    the per-example axes, batch axis fixed)."""
    INPUT_KIND = "any"

    dims: tuple = ()

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == "rnn":
            shape = [itype.timesteps, itype.size]
        elif itype.kind == "cnn":
            shape = [itype.height, itype.width, itype.channels]
        else:
            shape = [itype.size]
        out = [shape[d - 1] for d in self.dims]
        if len(out) == 1:
            return InputType.feed_forward(out[0])
        if len(out) == 2:
            return InputType.recurrent(out[1], out[0])
        if len(out) == 3:
            return InputType.convolutional(out[0], out[1], out[2])
        raise ValueError(f"PermuteLayer: unsupported rank {len(out)}")

    def feed_forward_mask(self, mask, itype):
        # the permutation moves the time axis; a [b, t] mask indexed on the
        # old axis would mask the wrong positions — drop it
        return None

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        perm = (0,) + tuple(d for d in self.dims)
        return jnp.transpose(x, perm), variables.get("state", {})


@register_serde
@dataclass
class RepeatVector(LayerConf):
    """Repeat a [b, f] feature vector n times → [b, n, f] (Keras
    ``RepeatVector``; reference ``nn/conf/layers/misc/RepeatVector`` role)."""
    INPUT_KIND = "ff"

    n: int = 1

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(itype.size, self.n)

    def feed_forward_mask(self, mask, itype):
        # every repeated step is a real step: all-valid (None) downstream
        return None

    def apply(self, variables, x, *, train=False, key=None, mask=None):
        return (jnp.repeat(x[:, None, :], self.n, axis=1),
                variables.get("state", {}))
